"""Differentiable-solve gradient checks (``porqua_tpu/qp/diff.py``).

Every gradient is validated against central finite differences of the
full solver in f64 — the implicit-function vjp must agree with "solve
the perturbed problem" wherever the active set is stable. The
reference cannot do any of this: its solver boundary
(``src/qp_problems.py:211``) is opaque to autodiff.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.diff import solve_qp_diff
from porqua_tpu.qp.solve import SolverParams, Status, solve_qp

PARAMS = SolverParams(max_iter=50000, eps_abs=1e-11, eps_rel=1e-11)


def _tracking_problem(rng, n=8, T=24, ub=0.4):
    """Strictly convex tracking QP: budget equality + box, a few box
    actives at the solution (ub tight enough to bind)."""
    X = rng.standard_normal((T, n)) * 0.1
    w_true = rng.dirichlet(np.ones(n) * 0.5)
    y = X @ w_true
    return X, y, ub


def _build_qp(X, y, ub, ridge=0.0):
    n = X.shape[1]
    dtype = X.dtype
    P = 2.0 * X.T @ X + 2.0 * ridge * jnp.eye(n, dtype=dtype)
    q = -2.0 * X.T @ y
    return CanonicalQP(
        P=P, q=q,
        C=jnp.ones((1, n), dtype), l=jnp.ones(1, dtype),
        u=jnp.ones(1, dtype),
        lb=jnp.zeros(n, dtype), ub=jnp.full(n, ub, dtype),
        var_mask=jnp.ones(n, dtype), row_mask=jnp.ones(1, dtype),
        constant=jnp.dot(y, y),
    )


def _fd_grad(loss_of_theta, theta, h=1e-6):
    g = np.zeros_like(theta)
    flat = theta.reshape(-1)
    for i in range(flat.size):
        tp, tm = flat.copy(), flat.copy()
        tp[i] += h
        tm[i] -= h
        g.reshape(-1)[i] = (
            loss_of_theta(tp.reshape(theta.shape))
            - loss_of_theta(tm.reshape(theta.shape))
        ) / (2 * h)
    return g


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(5)
    X, y, ub = _tracking_problem(rng)
    c = rng.standard_normal(X.shape[1])
    return (jnp.asarray(X, jnp.float64), jnp.asarray(y, jnp.float64), ub,
            jnp.asarray(c, jnp.float64))


def test_solution_has_mixed_active_set(problem):
    """Preflight: the fixture problem must bind some box bounds but not
    all (else the gradient checks would not exercise both branches)."""
    X, y, ub, _ = problem
    sol = solve_qp(_build_qp(X, y, ub), PARAMS)
    assert bool(sol.status == Status.SOLVED)
    at_ub = int(np.sum(np.asarray(sol.x) > ub - 1e-8))
    at_lb = int(np.sum(np.asarray(sol.x) < 1e-8))
    assert at_ub + at_lb > 0
    assert at_ub + at_lb < X.shape[1]


def test_grad_q_matches_finite_differences(problem):
    X, y, ub, c = problem
    qp0 = _build_qp(X, y, ub)

    def loss_jax(q):
        return jnp.dot(c, solve_qp_diff(qp0._replace(q=q), PARAMS))

    g = jax.grad(loss_jax)(qp0.q)

    def loss_fd(q_np):
        return float(jnp.dot(
            c, solve_qp(qp0._replace(q=jnp.asarray(q_np)), PARAMS).x))

    g_fd = _fd_grad(loss_fd, np.asarray(qp0.q))
    np.testing.assert_allclose(np.asarray(g), g_fd, rtol=1e-5, atol=1e-7)


def test_grad_ridge_through_P_matches_finite_differences(problem):
    """The canonical tuning loop: d(loss)/d(ridge) flows through
    P = 2 X'X + 2 ridge I."""
    X, y, ub, c = problem

    def loss_jax(ridge):
        return jnp.dot(c, solve_qp_diff(_build_qp(X, y, ub, ridge), PARAMS))

    g = float(jax.grad(loss_jax)(jnp.asarray(0.05, jnp.float64)))

    h = 1e-6

    def loss_at(r):
        return float(jnp.dot(c, solve_qp(_build_qp(X, y, ub, r), PARAMS).x))

    g_fd = (loss_at(0.05 + h) - loss_at(0.05 - h)) / (2 * h)
    np.testing.assert_allclose(g, g_fd, rtol=1e-5)


def test_grad_data_through_P_q_matches_finite_differences(problem):
    """Gradients w.r.t. the raw return window X flow through BOTH
    P = 2 X'X and q = -2 X'y simultaneously."""
    X, y, ub, c = problem

    def loss_jax(Xv):
        return jnp.dot(c, solve_qp_diff(_build_qp(Xv, y, ub), PARAMS))

    g = np.asarray(jax.grad(loss_jax)(X))

    def loss_fd(X_np):
        return float(jnp.dot(
            c, solve_qp(_build_qp(jnp.asarray(X_np), y, ub), PARAMS).x))

    # Spot-check a handful of entries (full (T, n) FD is slow).
    rng = np.random.default_rng(0)
    idx = [(int(i), int(j))
           for i, j in zip(rng.integers(0, X.shape[0], 6),
                           rng.integers(0, X.shape[1], 6))]
    h = 1e-6
    X_np = np.asarray(X)
    for (i, j) in idx:
        Xp, Xm = X_np.copy(), X_np.copy()
        Xp[i, j] += h
        Xm[i, j] -= h
        fd = (loss_fd(Xp) - loss_fd(Xm)) / (2 * h)
        np.testing.assert_allclose(g[i, j], fd, rtol=2e-4, atol=1e-7)


def test_grad_active_bound_matches_fd_and_inactive_is_zero(problem):
    X, y, ub, c = problem
    qp0 = _build_qp(X, y, ub)
    sol = solve_qp(qp0, PARAMS)
    x = np.asarray(sol.x)
    i_act = int(np.argmax(x))          # at ub (fixture guarantees one)
    assert x[i_act] > ub - 1e-8
    i_inact = int(np.argmin(np.abs(x - np.median(x))))  # strictly inside

    def loss_jax(ub_vec):
        return jnp.dot(c, solve_qp_diff(qp0._replace(ub=ub_vec), PARAMS))

    g = np.asarray(jax.grad(loss_jax)(qp0.ub))

    h = 1e-6

    def loss_at(i, delta):
        ub_v = np.asarray(qp0.ub).copy()
        ub_v[i] += delta
        return float(jnp.dot(
            c, solve_qp(qp0._replace(ub=jnp.asarray(ub_v)), PARAMS).x))

    fd_act = (loss_at(i_act, h) - loss_at(i_act, -h)) / (2 * h)
    np.testing.assert_allclose(g[i_act], fd_act, rtol=1e-5, atol=1e-9)
    assert abs(g[i_inact]) < 1e-8


def test_grad_budget_bound_matches_finite_differences(problem):
    """The equality row's bound (l == u == budget): move both together."""
    X, y, ub, c = problem
    qp0 = _build_qp(X, y, ub)

    def loss_jax(budget):
        b = jnp.full(1, budget, jnp.float64)
        return jnp.dot(
            c, solve_qp_diff(qp0._replace(l=b, u=b), PARAMS))

    g = float(jax.grad(loss_jax)(jnp.asarray(1.0, jnp.float64)))

    h = 1e-6

    def loss_at(budget):
        b = jnp.full(1, budget, jnp.float64)
        return float(jnp.dot(
            c, solve_qp(qp0._replace(l=b, u=b), PARAMS).x))

    g_fd = (loss_at(1.0 + h) - loss_at(1.0 - h)) / (2 * h)
    np.testing.assert_allclose(g, g_fd, rtol=1e-5)


def test_vmap_grad_composes(problem):
    """jax.vmap over a batch of dates + jax.grad through the summed
    tracking error — the shape every tuning loop uses."""
    X, y, ub, _ = problem
    rng = np.random.default_rng(9)
    Xs = jnp.asarray(rng.standard_normal((3,) + X.shape) * 0.1)
    w_true = rng.dirichlet(np.ones(X.shape[1]))
    ys = jnp.einsum("bti,i->bt", Xs, jnp.asarray(w_true))

    def loss(ridge):
        def one(Xb, yb):
            xw = solve_qp_diff(_build_qp(Xb, yb, ub, ridge), PARAMS)
            r = Xb @ xw - yb
            return jnp.mean(r * r)
        return jnp.sum(jax.vmap(one)(Xs, ys))

    g = float(jax.grad(loss)(jnp.asarray(0.02, jnp.float64)))
    h = 1e-6
    g_fd = (float(loss(jnp.asarray(0.02 + h)))
            - float(loss(jnp.asarray(0.02 - h)))) / (2 * h)
    np.testing.assert_allclose(g, g_fd, rtol=1e-4)
    # Ridge shrinks toward equal weight, away from the LS optimum: the
    # tracking error must be increasing in ridge here.
    assert g > 0


def test_unsolved_problem_gets_zero_gradient(problem):
    """Infeasible problem (box caps sum below the budget): status is
    not SOLVED and the cotangent is zeroed, not garbage."""
    X, y, _, c = problem
    n = X.shape[1]
    qp_bad = _build_qp(X, y, 0.05)  # sum(ub) = 0.4 < 1 = budget
    short = SolverParams(max_iter=2000, eps_abs=1e-9, eps_rel=1e-9)

    def loss_jax(q):
        return jnp.dot(c, solve_qp_diff(qp_bad._replace(q=q), short))

    sol = solve_qp(qp_bad, short)
    assert not bool(sol.status == Status.SOLVED)
    g = np.asarray(jax.grad(loss_jax)(qp_bad.q))
    np.testing.assert_allclose(g, np.zeros(n), atol=0.0)


def test_factored_adjoint_path_matches_finite_differences():
    """When the objective carries its factor (Pf, capacitance dim
    r + m < n), the adjoint dispatches to the exact-pinning factored
    KKT solve — same machinery as the polish. Gradient parity with
    finite differences pins that path specifically."""
    rng = np.random.default_rng(17)
    T, n = 16, 30
    X = jnp.asarray(rng.standard_normal((T, n)) * 0.1)
    w_true = rng.dirichlet(np.ones(n) * 0.5)
    y = X @ jnp.asarray(w_true)
    c = jnp.asarray(rng.standard_normal(n))

    def build(q_shift):
        dtype = X.dtype
        P = 2.0 * X.T @ X + 0.02 * jnp.eye(n, dtype=dtype)
        q = -2.0 * X.T @ y + q_shift
        return CanonicalQP(
            P=P, q=q,
            C=jnp.ones((1, n), dtype), l=jnp.ones(1, dtype),
            u=jnp.ones(1, dtype),
            lb=jnp.zeros(n, dtype), ub=jnp.full(n, 0.2, dtype),
            var_mask=jnp.ones(n, dtype), row_mask=jnp.ones(1, dtype),
            constant=jnp.dot(y, y),
            Pf=X, Pdiag=jnp.full(n, 0.02, dtype),
        )

    from porqua_tpu.qp.polish import polish_capacitance_dim
    assert polish_capacitance_dim(build(jnp.zeros(n))) == T + 1

    def loss_jax(q_shift):
        return jnp.dot(c, solve_qp_diff(build(q_shift), PARAMS))

    g = np.asarray(jax.grad(loss_jax)(jnp.zeros(n, jnp.float64)))

    h = 1e-6

    def loss_at(q_np):
        return float(jnp.dot(
            c, solve_qp(build(jnp.asarray(q_np)), PARAMS).x))

    for i in [0, 7, 15, 29]:
        qp_, qm_ = np.zeros(n), np.zeros(n)
        qp_[i] += h
        qm_[i] -= h
        fd = (loss_at(qp_) - loss_at(qm_)) / (2 * h)
        np.testing.assert_allclose(g[i], fd, rtol=1e-4, atol=1e-8)


def test_grad_constraint_matrix_matches_finite_differences():
    """C_bar = -(nu u' + wC x') with an ACTIVE inequality row — the
    least-trivial vjp formula, pinned against finite differences (the
    other tests hold C fixed)."""
    rng = np.random.default_rng(23)
    n, T = 6, 18
    X = jnp.asarray(rng.standard_normal((T, n)) * 0.1)
    w_true = rng.dirichlet(np.ones(n))
    y = X @ jnp.asarray(w_true)
    c = jnp.asarray(rng.standard_normal(n))
    # Rows: budget equality + a sector-cap inequality tight enough to
    # bind (sum of first three weights <= cap below their LS optimum).
    sector = jnp.asarray(np.array([1.0, 1.0, 1.0, 0, 0, 0]))

    def build(C2):
        dtype = X.dtype
        C = jnp.stack([jnp.ones(n, dtype), C2])
        inf = jnp.asarray(jnp.inf, dtype)
        return CanonicalQP(
            P=2.0 * X.T @ X + 0.01 * jnp.eye(n, dtype=dtype),
            q=-2.0 * X.T @ y,
            C=C, l=jnp.asarray([1.0, -jnp.inf]), u=jnp.asarray([1.0, 0.35]),
            lb=jnp.full(n, -inf), ub=jnp.full(n, inf),
            var_mask=jnp.ones(n, dtype), row_mask=jnp.ones(2, dtype),
            constant=jnp.dot(y, y),
        )

    sol = solve_qp(build(sector), PARAMS)
    assert bool(sol.status == Status.SOLVED)
    # The cap must actually bind for the test to exercise C_bar.
    assert abs(float(sol.z[1]) - 0.35) < 1e-7, float(sol.z[1])

    def loss_jax(C2):
        return jnp.dot(c, solve_qp_diff(build(C2), PARAMS))

    g = np.asarray(jax.grad(loss_jax)(sector))

    h = 1e-6

    def loss_at(C2_np):
        return float(jnp.dot(c, solve_qp(build(jnp.asarray(C2_np)), PARAMS).x))

    s_np = np.asarray(sector)
    for i in range(n):
        cp, cm = s_np.copy(), s_np.copy()
        cp[i] += h
        cm[i] -= h
        fd = (loss_at(cp) - loss_at(cm)) / (2 * h)
        np.testing.assert_allclose(g[i], fd, rtol=1e-4, atol=1e-8)


class TestL1Diff:
    """Native L1-prox path gradients (solve_qp_l1_diff) vs finite
    differences: the turnover-penalty knob and the centers (previous
    holdings) at a solution with BOTH kink-resters and movers."""

    @pytest.fixture(scope="class")
    def l1_problem(self):
        from porqua_tpu.qp.diff import solve_qp_l1_diff  # noqa: F401

        rng = np.random.default_rng(31)
        n, T = 10, 40
        X = jnp.asarray(rng.standard_normal((T, n)) * 0.1)
        w_true = rng.dirichlet(np.ones(n))
        y = X @ jnp.asarray(w_true)
        # Previous holdings near the optimum: with a mid-sized penalty
        # some coordinates stay exactly at c (kink-resters), others
        # move (smooth) — verified in test_classification_is_mixed.
        c_prev = jnp.asarray(rng.dirichlet(np.ones(n)))
        lam = 2e-3
        cvec = jnp.asarray(rng.standard_normal(n))
        return X, y, c_prev, lam, cvec

    def _build(self, X, y):
        n = X.shape[1]
        dtype = X.dtype
        return CanonicalQP(
            P=2.0 * X.T @ X + 0.01 * jnp.eye(n, dtype=dtype),
            q=-2.0 * X.T @ y,
            C=jnp.ones((1, n), dtype), l=jnp.ones(1, dtype),
            u=jnp.ones(1, dtype),
            lb=jnp.zeros(n, dtype), ub=jnp.ones(n, dtype),
            var_mask=jnp.ones(n, dtype), row_mask=jnp.ones(1, dtype),
            constant=jnp.dot(y, y),
        )

    def test_classification_is_mixed(self, l1_problem):
        X, y, c_prev, lam, _ = l1_problem
        n = X.shape[1]
        sol = solve_qp(self._build(X, y), PARAMS,
                       l1_weight=jnp.full(n, lam), l1_center=c_prev)
        assert bool(sol.status == Status.SOLVED)
        at_c = np.abs(np.asarray(sol.x) - np.asarray(c_prev)) < 1e-9
        assert 0 < int(at_c.sum()) < n, at_c

    def test_grad_l1_weight_matches_fd(self, l1_problem):
        from porqua_tpu.qp.diff import solve_qp_l1_diff

        X, y, c_prev, lam, cvec = l1_problem
        n = X.shape[1]
        qp0 = self._build(X, y)

        def loss_jax(lam_s):
            return jnp.dot(cvec, solve_qp_l1_diff(
                qp0, jnp.full(n, lam_s), c_prev, PARAMS))

        g = float(jax.grad(loss_jax)(jnp.asarray(lam, jnp.float64)))

        h = 1e-7

        def loss_at(ls):
            return float(jnp.dot(cvec, solve_qp(
                qp0, PARAMS, l1_weight=jnp.full(n, ls),
                l1_center=c_prev).x))

        g_fd = (loss_at(lam + h) - loss_at(lam - h)) / (2 * h)
        np.testing.assert_allclose(g, g_fd, rtol=1e-3, atol=1e-8)

    def test_grad_l1_center_matches_fd(self, l1_problem):
        from porqua_tpu.qp.diff import solve_qp_l1_diff

        X, y, c_prev, lam, cvec = l1_problem
        n = X.shape[1]
        qp0 = self._build(X, y)
        lamv = jnp.full(n, lam)

        def loss_jax(cv):
            return jnp.dot(cvec, solve_qp_l1_diff(qp0, lamv, cv, PARAMS))

        g = np.asarray(jax.grad(loss_jax)(c_prev))

        sol = solve_qp(qp0, PARAMS, l1_weight=lamv, l1_center=c_prev)
        at_c = np.abs(np.asarray(sol.x) - np.asarray(c_prev)) < 1e-9
        h = 1e-7
        c_np = np.asarray(c_prev)

        def loss_at(cv):
            return float(jnp.dot(cvec, solve_qp(
                qp0, PARAMS, l1_weight=lamv,
                l1_center=jnp.asarray(cv)).x))

        # Check one kink-rester (nonzero grad: moving its anchor moves
        # the pinned weight) and one mover (zero grad locally).
        i_kink = int(np.argmax(at_c))
        i_move = int(np.argmax(~at_c))
        for i in (i_kink, i_move):
            cp, cm = c_np.copy(), c_np.copy()
            cp[i] += h
            cm[i] -= h
            fd = (loss_at(cp) - loss_at(cm)) / (2 * h)
            np.testing.assert_allclose(g[i], fd, rtol=1e-3, atol=1e-7)
        assert abs(g[i_move]) < 1e-7

    def test_grad_q_matches_fd_with_l1(self, l1_problem):
        from porqua_tpu.qp.diff import solve_qp_l1_diff

        X, y, c_prev, lam, cvec = l1_problem
        n = X.shape[1]
        qp0 = self._build(X, y)
        lamv = jnp.full(n, lam)

        def loss_jax(q):
            return jnp.dot(cvec, solve_qp_l1_diff(
                qp0._replace(q=q), lamv, c_prev, PARAMS))

        g = np.asarray(jax.grad(loss_jax)(qp0.q))

        h = 1e-7
        q_np = np.asarray(qp0.q)

        def loss_at(qv):
            return float(jnp.dot(cvec, solve_qp(
                qp0._replace(q=jnp.asarray(qv)), PARAMS,
                l1_weight=lamv, l1_center=c_prev).x))

        for i in [0, 4, 9]:
            qp_, qm_ = q_np.copy(), q_np.copy()
            qp_[i] += h
            qm_[i] -= h
            fd = (loss_at(qp_) - loss_at(qm_)) / (2 * h)
            np.testing.assert_allclose(g[i], fd, rtol=1e-3, atol=1e-7)


def test_l1_center_on_box_bound_routes_cotangent_to_box():
    """The natural turnover corner: previous holding 0 for an asset
    whose weight stays 0 — the kink pin and the lb coincide. Per the
    documented precedence the BOX cotangent wins: lb gets the
    sensitivity, the center gets zero."""
    from porqua_tpu.qp.diff import solve_qp_l1_diff

    rng = np.random.default_rng(41)
    n, T = 8, 30
    X = jnp.asarray(rng.standard_normal((T, n)) * 0.1)
    w_true = np.zeros(n)
    w_true[: n - 2] = rng.dirichlet(np.ones(n - 2))  # last 2 assets dead
    y = X @ jnp.asarray(w_true)
    c_prev = jnp.asarray(np.concatenate(
        [rng.dirichlet(np.ones(n - 2)), [0.0, 0.0]]))
    lam = jnp.full(n, 5e-3, jnp.float64)
    dtype = X.dtype
    qp0 = CanonicalQP(
        P=2.0 * X.T @ X + 0.01 * jnp.eye(n, dtype=dtype),
        q=-2.0 * X.T @ y,
        C=jnp.ones((1, n), dtype), l=jnp.ones(1, dtype),
        u=jnp.ones(1, dtype),
        lb=jnp.zeros(n, dtype), ub=jnp.ones(n, dtype),
        var_mask=jnp.ones(n, dtype), row_mask=jnp.ones(1, dtype),
        constant=jnp.dot(y, y),
    )
    sol = solve_qp(qp0, PARAMS, l1_weight=lam, l1_center=c_prev)
    assert bool(sol.status == Status.SOLVED)
    dead = np.where(np.asarray(sol.x) < 1e-9)[0]
    assert dead.size > 0, np.asarray(sol.x)
    i = int(dead[0])
    assert float(c_prev[i]) == 0.0  # pin and lb coincide at this corner

    cvec = jnp.asarray(rng.standard_normal(n))

    def loss_c(cv):
        return jnp.dot(cvec, solve_qp_l1_diff(qp0, lam, cv, PARAMS))

    def loss_lb(lb):
        return jnp.dot(cvec, solve_qp_l1_diff(
            qp0._replace(lb=lb), lam, c_prev, PARAMS))

    g_c = np.asarray(jax.grad(loss_c)(c_prev))
    g_lb = np.asarray(jax.grad(loss_lb)(qp0.lb))
    assert abs(g_c[i]) < 1e-10, g_c[i]
    # One-sided FD upward (moving lb up drags the pinned weight with
    # it) must match the reported lb gradient.
    h = 1e-7
    lb_p = np.zeros(n)
    lb_p[i] = h
    up = float(jnp.dot(cvec, solve_qp(
        qp0._replace(lb=jnp.asarray(lb_p)), PARAMS,
        l1_weight=lam, l1_center=c_prev).x))
    base = float(jnp.dot(cvec, sol.x))
    np.testing.assert_allclose(g_lb[i], (up - base) / h, rtol=1e-3,
                               atol=1e-7)


def test_l1_grad_with_near_saturated_rester_subgradients():
    """Regression: kink-resters can carry subgradients arbitrarily
    close below w (here up to 0.91 w) while movers saturate |mu| = w
    exactly — the polish's noisy-iterate 0.75 w margin misclassified
    them in the adjoint and produced gradients wrong by sign. The
    solution-mode margin (classify_l1 dual_mode="solution") must match
    finite differences on exactly that problem."""
    from porqua_tpu.qp.diff import solve_qp_l1_diff

    rng = np.random.default_rng(11)
    N, T = 16, 40
    w_prev = jnp.asarray(rng.dirichlet(np.ones(N)))
    w_true = rng.dirichlet(np.ones(N))
    Xs = rng.standard_normal((3, 2 * T, N)) * 0.01
    ys = Xs @ w_true + rng.standard_normal((3, 2 * T)) * 0.002
    X, y = jnp.asarray(Xs[2, :T]), jnp.asarray(ys[2, :T])
    lam = 10.0 ** -3.2
    dtype = X.dtype
    qp0 = CanonicalQP(
        P=2.0 * X.T @ X, q=-2.0 * X.T @ y,
        C=jnp.ones((1, N), dtype), l=jnp.ones(1, dtype),
        u=jnp.ones(1, dtype),
        lb=jnp.zeros(N, dtype), ub=jnp.ones(N, dtype),
        var_mask=jnp.ones(N, dtype), row_mask=jnp.ones(1, dtype),
        constant=jnp.dot(y, y),
    )
    sol = solve_qp(qp0, PARAMS, l1_weight=jnp.full(N, lam),
                   l1_center=w_prev)
    mu_over_lam = np.abs(np.asarray(sol.mu)) / lam
    at_c = np.abs(np.asarray(sol.x) - np.asarray(w_prev)) < 1e-9
    # Preflight: the fixture must contain the failure regime.
    assert float(mu_over_lam[at_c].max()) > 0.8, mu_over_lam[at_c]

    cvec = jnp.asarray(rng.standard_normal(N))

    def loss_jax(lam_s):
        return jnp.dot(cvec, solve_qp_l1_diff(
            qp0, jnp.full(N, lam_s), w_prev, PARAMS))

    g = float(jax.grad(loss_jax)(jnp.asarray(lam, jnp.float64)))
    h = 1e-8

    def loss_at(ls):
        return float(jnp.dot(cvec, solve_qp(
            qp0, PARAMS, l1_weight=jnp.full(N, ls),
            l1_center=w_prev).x))

    fd = (loss_at(lam + h) - loss_at(lam - h)) / (2 * h)
    np.testing.assert_allclose(g, fd, rtol=1e-4, atol=1e-9)


def test_l1_weight_zero_has_one_sided_gradient():
    """d(loss)/d(w_i) at w_i = 0 is the one-sided limit
    -u_i sign(x_i - c_i), not a dead zero: a tuning loop starting at
    zero penalty must receive a pull."""
    from porqua_tpu.qp.diff import solve_qp_l1_diff

    rng = np.random.default_rng(31)
    n, T = 10, 40
    X = jnp.asarray(rng.standard_normal((T, n)) * 0.1)
    w_true = rng.dirichlet(np.ones(n))
    y = X @ jnp.asarray(w_true)
    c_prev = jnp.asarray(rng.dirichlet(np.ones(n)))
    cvec = jnp.asarray(rng.standard_normal(n))
    dtype = X.dtype
    qp0 = CanonicalQP(
        P=2.0 * X.T @ X + 0.01 * jnp.eye(n, dtype=dtype),
        q=-2.0 * X.T @ y,
        C=jnp.ones((1, n), dtype), l=jnp.ones(1, dtype),
        u=jnp.ones(1, dtype),
        lb=jnp.zeros(n, dtype), ub=jnp.ones(n, dtype),
        var_mask=jnp.ones(n, dtype), row_mask=jnp.ones(1, dtype),
        constant=jnp.dot(y, y),
    )

    def loss_jax(lam_s):
        return jnp.dot(cvec, solve_qp_l1_diff(
            qp0, jnp.full(n, lam_s), c_prev, PARAMS))

    g = float(jax.grad(loss_jax)(jnp.asarray(0.0, jnp.float64)))
    h = 1e-7
    fd_right = (float(loss_jax(jnp.asarray(h))) -
                float(loss_jax(jnp.asarray(0.0)))) / h
    assert abs(g) > 1e-3, g
    np.testing.assert_allclose(g, fd_right, rtol=1e-3)


def test_l1_center_none_is_differentiable():
    """l1_center=None (centered at zero, the polish convention) must
    work under jax.grad, with gradients matching an explicit zero
    center."""
    from porqua_tpu.qp.diff import solve_qp_l1_diff

    rng = np.random.default_rng(13)
    n, T = 8, 24
    X = jnp.asarray(rng.standard_normal((T, n)) * 0.1)
    y = X @ jnp.asarray(rng.dirichlet(np.ones(n)))
    cvec = jnp.asarray(rng.standard_normal(n))
    dtype = X.dtype
    qp0 = CanonicalQP(
        P=2.0 * X.T @ X + 0.01 * jnp.eye(n, dtype=dtype),
        q=-2.0 * X.T @ y,
        C=jnp.ones((1, n), dtype), l=jnp.ones(1, dtype),
        u=jnp.ones(1, dtype),
        lb=jnp.full(n, -1.0, dtype), ub=jnp.ones(n, dtype),
        var_mask=jnp.ones(n, dtype), row_mask=jnp.ones(1, dtype),
        constant=jnp.dot(y, y),
    )
    lam = 1e-3

    def loss_none(lam_s):
        return jnp.dot(cvec, solve_qp_l1_diff(
            qp0, jnp.full(n, lam_s), None, PARAMS))

    def loss_zero(lam_s):
        return jnp.dot(cvec, solve_qp_l1_diff(
            qp0, jnp.full(n, lam_s), jnp.zeros(n, jnp.float64), PARAMS))

    g_none = float(jax.grad(loss_none)(jnp.asarray(lam, jnp.float64)))
    g_zero = float(jax.grad(loss_zero)(jnp.asarray(lam, jnp.float64)))
    np.testing.assert_allclose(g_none, g_zero, rtol=1e-10)


def test_grad_through_turnover_coupled_scan():
    """The sequential cost-aware backtest: lax.scan chains each date's
    solution into the next date's L1 center (w_prev). solve_qp_l1_diff
    composes with scan, so d(total net)/d(lambda) backpropagates
    through the whole date chain — including the c_bar cotangents that
    flow BACKWARD across dates. Checked against finite differences of
    the full chained solve."""
    from porqua_tpu.qp.diff import solve_qp_l1_diff

    rng = np.random.default_rng(53)
    n, T, B = 8, 30, 4
    Xs = jnp.asarray(rng.standard_normal((B, T, n)) * 0.1)
    w_true = rng.dirichlet(np.ones(n))
    ys = jnp.einsum("bti,i->bt", Xs, jnp.asarray(w_true))
    w0 = jnp.asarray(rng.dirichlet(np.ones(n)))

    def chained_net(lam):
        def body(c_prev, Xy):
            X, y = Xy
            x = solve_qp_l1_diff(
                _build_qp(X, y, ub=1.0, ridge=0.005), jnp.full(n, lam),
                c_prev, PARAMS)
            te = jnp.sqrt(jnp.mean((X @ x - y) ** 2))
            cost = 0.003 * jnp.sum(jnp.abs(x - c_prev))
            return x, te + cost

        _, nets = jax.lax.scan(body, w0, (Xs, ys))
        return jnp.sum(nets)

    lam0 = 1.5e-3
    g = float(jax.grad(chained_net)(jnp.asarray(lam0, jnp.float64)))
    h = 1e-7
    fd = (float(chained_net(jnp.asarray(lam0 + h)))
          - float(chained_net(jnp.asarray(lam0 - h)))) / (2 * h)
    np.testing.assert_allclose(g, fd, rtol=1e-3, atol=1e-8)
    assert abs(g) > 1e-6  # the chain is genuinely lambda-sensitive


def test_grad_f32_agrees_with_f64_direction():
    """The TPU dtype contract: f32 gradients through the solve are
    noisier (sqrt(f32-eps) adjoint regularization, looser solve) but
    must agree with the f64 gradient in direction and to ~10% in
    magnitude on a well-conditioned problem — good enough for the
    tuning loops they feed."""
    rng = np.random.default_rng(3)
    n, T = 8, 24
    X64 = jnp.asarray(rng.standard_normal((T, n)) * 0.1, jnp.float64)
    y64 = X64 @ jnp.asarray(rng.dirichlet(np.ones(n)), jnp.float64)
    c = rng.standard_normal(n)

    def grad_at(dtype, params):
        X, y = X64.astype(dtype), y64.astype(dtype)
        cv = jnp.asarray(c, dtype)

        def loss(ridge):
            return jnp.dot(cv, solve_qp_diff(
                _build_qp(X, y, ub=0.4, ridge=ridge), params))

        return float(jax.grad(loss)(jnp.asarray(0.05, dtype)))

    g64 = grad_at(jnp.float64, PARAMS)
    g32 = grad_at(jnp.float32,
                  SolverParams(max_iter=20000, eps_abs=1e-6, eps_rel=1e-6))
    assert np.sign(g64) == np.sign(g32)
    np.testing.assert_allclose(g32, g64, rtol=0.1)


def test_grad_through_net_pnl_accounting():
    """End-to-end P&L differentiability (round-4 verdict item 7): the
    turnover-coupled scan of native-L1 solves composes with the DEVICE
    ACCOUNTING ENGINE (accounting.simulate — drifted weights, levels,
    variable costs) into a net-Sharpe objective, and d(net Sharpe)/
    d(lambda) through solver + P&L + compounding matches finite
    differences. This is the gradient examples/net_sharpe_tuning.py
    ascends."""
    from porqua_tpu.accounting import simulate
    from porqua_tpu.qp.diff import solve_qp_l1_diff

    rng = np.random.default_rng(21)
    n, window, d_reb, step = 6, 16, 3, 8
    T = window + d_reb * step + 1
    R = jnp.asarray(rng.standard_normal((T, n)) * 0.01
                    + 0.0004 * rng.standard_normal(n))
    w_true = rng.dirichlet(np.ones(n))
    y = R @ jnp.asarray(w_true) + 0.001 * jnp.asarray(
        rng.standard_normal(T))
    reb_idx = jnp.arange(window, window + d_reb * step, step)
    Xs = jnp.stack([R[int(i) - window:int(i)] for i in reb_idx])
    ys = jnp.stack([y[int(i) - window:int(i)] for i in reb_idx])
    w0 = jnp.full((n,), 1.0 / n)

    def net_sharpe(lam):
        def body(w_prev, Xy):
            X, yb = Xy
            w = solve_qp_l1_diff(_build_qp(X, yb, ub=1.0, ridge=0.01),
                                 jnp.full(n, lam), w_prev, PARAMS)
            return w, w

        _, ws = jax.lax.scan(body, w0, (Xs, ys))
        sim = simulate(ws, R, reb_idx, vc=0.005)
        nv = jnp.sum(sim.valid)
        mean = jnp.sum(sim.returns) / nv
        var = jnp.sum(jnp.where(sim.valid, (sim.returns - mean) ** 2,
                                0.0)) / (nv - 1.0)
        return mean / jnp.sqrt(var) * jnp.sqrt(252.0)

    lam0 = 4e-4  # inside the live region: some coordinates move
    g = float(jax.grad(net_sharpe)(jnp.asarray(lam0, jnp.float64)))
    h = 1e-7
    fd = (float(net_sharpe(jnp.asarray(lam0 + h)))
          - float(net_sharpe(jnp.asarray(lam0 - h)))) / (2 * h)
    np.testing.assert_allclose(g, fd, rtol=1e-3, atol=1e-6)
    assert abs(g) > 1e-3  # the P&L is genuinely lambda-sensitive
