"""Test configuration: CPU backend with 8 virtual devices.

XLA's CPU backend runs the same programs as TPU (the "fake backend" the
reference never had — SURVEY.md section 4), and 8 virtual host devices
let the multi-chip sharding paths compile and execute without hardware.
x64 is enabled so parity tests can run the solver at float64 against
float64 references; solver code is dtype-parametric.
"""

import os

# PORQUA_TPU_TESTS=1 switches the suite to real-hardware mode: the
# container's default backend (the TPU plugin) stays active, x64 stays
# off (TPU has no native f64), and only tests marked `tpu` make sense —
# run `PORQUA_TPU_TESTS=1 pytest -m tpu`. Default mode is the virtual
# 8-device CPU backend with x64 for parity references.
_TPU_MODE = os.environ.get("PORQUA_TPU_TESTS") == "1"

_flags = os.environ.get("XLA_FLAGS", "")
if not _TPU_MODE and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _TPU_MODE:
    # The environment's sitecustomize registers the axon TPU plugin and
    # sets jax_platforms="axon,cpu" via jax.config — which overrides any
    # JAX_PLATFORMS env var. Tests must run on the virtual-device CPU
    # backend, so the config (not the env) is the knob to set here.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: requires a real TPU backend (run with PORQUA_TPU_TESTS=1 "
        "pytest -m tpu); skipped otherwise",
    )
    config.addinivalue_line(
        "markers",
        "slow: multi-second subprocess tests (deselect with -m 'not slow')",
    )


def pytest_collection_modifyitems(config, items):
    if _TPU_MODE:
        if jax.default_backend() != "tpu":
            # TPU mode was requested but no TPU came up: x64 is off and
            # the CPU-reference tolerances are meaningless — skip
            # everything loudly rather than failing f64 tests en masse.
            skip = pytest.mark.skip(
                reason="PORQUA_TPU_TESTS=1 but no TPU backend initialized")
            for item in items:
                item.add_marker(skip)
            return
        skip = pytest.mark.skip(
            reason="real-TPU session runs only tpu-marked tests")
        for item in items:
            if "tpu" not in item.keywords:
                item.add_marker(skip)
    else:
        skip = pytest.mark.skip(
            reason="needs a real TPU (PORQUA_TPU_TESTS=1 and TPU backend)")
        for item in items:
            if "tpu" in item.keywords:
                item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(42)
