"""Test configuration: CPU backend with 8 virtual devices.

XLA's CPU backend runs the same programs as TPU (the "fake backend" the
reference never had — SURVEY.md section 4), and 8 virtual host devices
let the multi-chip sharding paths compile and execute without hardware.
x64 is enabled so parity tests can run the solver at float64 against
float64 references; solver code is dtype-parametric.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The environment's sitecustomize registers the axon TPU plugin and sets
# jax_platforms="axon,cpu" via jax.config — which overrides any
# JAX_PLATFORMS env var. Tests must run on the virtual-device CPU
# backend, so the config (not the env) is the knob to set here.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
