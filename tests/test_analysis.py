"""graftcheck (porqua_tpu.analysis): the AST rules, the guarded-by
lint, the jaxpr contracts, and the runtime sanitizer.

Two kinds of coverage: (1) seeded violations — one fixture per rule —
must each be detected with the right rule id and line number; (2) the
shipped ``porqua_tpu/`` tree must scan clean (the self-scan is the
regression gate that keeps the device-discipline invariants holding as
the codebase grows).
"""

import os
import textwrap

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

import porqua_tpu
from porqua_tpu.analysis import sanitize
from porqua_tpu.analysis.lint import scan_paths
from porqua_tpu.serve import BucketLadder, SolveError, SolveService

REPO_PKG = os.path.dirname(os.path.abspath(porqua_tpu.__file__))


def write_fixture(tmp_path, relpath, source):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return str(path)


def findings_for(tmp_path, relpath, source, rules=None):
    path = write_fixture(tmp_path, relpath, source)
    return [(f.rule, f.line) for f in scan_paths([path], rules=rules)]


# ---------------------------------------------------------------------------
# GC001 — precision pins
# ---------------------------------------------------------------------------

def test_gc001_unpinned_contraction_detected(tmp_path):
    hits = findings_for(tmp_path, "qp/bad.py", """\
        import jax.numpy as jnp

        def f(a, b):
            return jnp.dot(a, b)

        def pinned(a, b):
            return jnp.dot(a, b, precision="highest")
        """)
    assert hits == [("GC001", 4)]


def test_gc001_matmul_operator_on_jnp_operand(tmp_path):
    hits = findings_for(tmp_path, "qp/bad.py", """\
        import jax.numpy as jnp
        import numpy as np

        def f(a):
            c = jnp.eye(3)
            return c @ a

        def host_only(a):
            P = np.eye(3)
            return P @ a
        """)
    assert hits == [("GC001", 6)]  # numpy @ stays exempt


def test_gc001_matmul_on_params_of_jitted_fn(tmp_path):
    hits = findings_for(tmp_path, "qp/mod.py", """\
        import jax

        @jax.jit
        def f(x, P):
            return x @ P

        def host(x, P):
            return x @ P
        """, rules={"GC001"})
    assert hits == [("GC001", 5)]  # params are traced inside jit


def test_gc001_scoped_to_precision_modules(tmp_path):
    hits = findings_for(tmp_path, "models/fine.py", """\
        import jax.numpy as jnp

        def f(a, b):
            return jnp.dot(a, b)
        """)
    assert hits == []


def test_gc001_line_suppression(tmp_path):
    hits = findings_for(tmp_path, "qp/bad.py", """\
        import jax.numpy as jnp

        def f(a, b):
            return jnp.dot(a, b)  # graftcheck: disable=GC001
        """)
    assert hits == []


# ---------------------------------------------------------------------------
# GC002 — host syncs in jit-reachable code
# ---------------------------------------------------------------------------

def test_gc002_hazards_reachable_through_call_graph(tmp_path):
    hits = findings_for(tmp_path, "mod.py", """\
        import jax
        import numpy as np

        @jax.jit
        def hot(x):
            return helper(x)

        def helper(x):
            np.asarray(x)
            return x.item()

        def host_side(x):
            return float(np.asarray(x).sum())
        """)
    assert ("GC002", 9) in hits   # np.asarray in reachable helper
    assert ("GC002", 10) in hits  # .item() in reachable helper
    assert not any(line == 13 for _, line in hits)  # host code exempt


def test_gc002_scan_body_is_a_root(tmp_path):
    hits = findings_for(tmp_path, "mod.py", """\
        import jax

        def run(xs):
            def body(c, x):
                return c + x.item(), None
            return jax.lax.scan(body, 0.0, xs)
        """)
    assert hits == [("GC002", 5)]


def test_gc002_from_import_jit_roots(tmp_path):
    hits = findings_for(tmp_path, "mod.py", """\
        from jax import jit
        from jax.lax import scan

        @jit
        def hot(x):
            return x.item()

        def run(xs):
            def body(c, x):
                return c + float(x), None
            return scan(body, 0.0, xs)
        """, rules={"GC002"})
    assert ("GC002", 6) in hits   # @jit via from-import
    assert ("GC002", 10) in hits  # scan body via from-import


# ---------------------------------------------------------------------------
# GC003 — recompile hazards
# ---------------------------------------------------------------------------

def test_gc003_jit_in_loop_and_in_function(tmp_path):
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import jax

        def looped(fs, x):
            for f in fs:
                x = jax.jit(f)(x)
            return x

        def local(f, x):
            return jax.jit(f)(x)

        def aot(f, x):
            return jax.jit(f).lower(x).compile()

        class Holder:
            def prime(self, f):
                self._fn = jax.jit(f)
        """)
    assert ("GC003", 5) in hits
    assert ("GC003", 9) in hits
    assert not any(line in (12, 16) for _, line in hits)  # exemptions


def test_gc003_unhashable_static_default(tmp_path):
    hits = findings_for(tmp_path, "qp/mod.py", """\
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("opts",))
        def f(x, opts=[1, 2]):
            return x
        """)
    assert ("GC003", 6) in hits


# ---------------------------------------------------------------------------
# GC004 / GC005
# ---------------------------------------------------------------------------

def test_gc004_debug_hooks(tmp_path):
    hits = findings_for(tmp_path, "mod.py", """\
        import jax

        def f(x):
            jax.debug.print("x={}", x)
            breakpoint()
            return x
        """)
    assert ("GC004", 4) in hits and ("GC004", 5) in hits


def test_gc005_module_level_backend_init(tmp_path):
    hits = findings_for(tmp_path, "mod.py", """\
        import jax
        import jax.numpy as jnp

        EAGER = jnp.zeros(3)
        JITTED = jax.jit(lambda x: x)  # lazy: fine

        def lazy():
            return jnp.zeros(3)
        """, rules={"GC005"})
    assert hits == [("GC005", 4)]


def test_gc005_ignores_defs_nested_in_module_level_blocks(tmp_path):
    hits = findings_for(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        try:
            import scipy  # noqa: F401
        except ImportError:
            def fallback():
                return jnp.zeros(3)

        def g(x=jnp.zeros(3)):  # default DOES run at import
            return x
        """, rules={"GC005"})
    assert hits == [("GC005", 9)]


def test_file_suppression(tmp_path):
    hits = findings_for(tmp_path, "mod.py", """\
        # graftcheck: disable-file=GC005
        import jax.numpy as jnp

        EAGER = jnp.zeros(3)
        """, rules={"GC005"})
    assert hits == []


# ---------------------------------------------------------------------------
# GC006 — guarded-by
# ---------------------------------------------------------------------------

def test_gc006_guarded_by(tmp_path):
    hits = findings_for(tmp_path, "serve/locks.py", """\
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}  # guarded-by: self._lock

            def good(self, k, v):
                with self._lock:
                    self._data[k] = v

            def nested_ok(self, k, v):
                if k:
                    with self._lock:
                        self._data.update({k: v})

            def bad_assign(self, k, v):
                self._data[k] = v

            def bad_method(self, k):
                self._data.pop(k)

            def held(self, k):  # guarded-by: self._lock
                del self._data[k]
        """)
    assert hits == [("GC006", 18), ("GC006", 21)]


def test_gc006_setitem_slice_and_rotate_mutators(tmp_path):
    # `__setitem__` spelled as a call (the slice-store idiom the
    # subscript-target check can't see) and deque.rotate are
    # mutations; both must require the lock.
    hits = findings_for(tmp_path, "serve/locks.py", """\
        import collections
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._buf = [0] * 8  # guarded-by: self._lock
                self._dq = collections.deque()  # guarded-by: self._lock

            def bad_slice_call(self, v):
                self._buf.__setitem__(slice(0, 2), v)

            def bad_rotate(self):
                self._dq.rotate(1)

            def good(self, v):
                with self._lock:
                    self._buf.__setitem__(slice(0, 2), v)
                    self._dq.rotate(1)
        """)
    assert hits == [("GC006", 11), ("GC006", 14)]


def test_gc006_nested_def_does_not_inherit_lock(tmp_path):
    hits = findings_for(tmp_path, "serve/locks.py", """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: self._lock

            def spawn(self):
                with self._lock:
                    def worker():
                        self._n += 1
                    return worker
        """)
    assert hits == [("GC006", 11)]


# ---------------------------------------------------------------------------
# self-scan: the shipped tree is clean
# ---------------------------------------------------------------------------

def test_self_scan_shipped_tree_is_clean():
    findings = scan_paths([REPO_PKG])
    assert findings == [], "\n".join(f.format() for f in findings)


# ---------------------------------------------------------------------------
# jaxpr contracts
# ---------------------------------------------------------------------------

def test_contracts_entry_points_clean():
    from porqua_tpu.analysis import contracts

    findings = contracts.check_entry_points()
    assert findings == [], "\n".join(f.format() for f in findings)


def test_contracts_run_batch_clean(rng):
    from porqua_tpu import (
        BacktestService,
        LeastSquares,
        OptimizationItemBuilder,
        SelectionItemBuilder,
    )
    from porqua_tpu.analysis import contracts
    from porqua_tpu.builders import (
        bibfn_bm_series,
        bibfn_box_constraints,
        bibfn_budget_constraint,
        bibfn_return_series,
        bibfn_selection_data,
    )

    n_assets, n_days = 6, 140
    dates = pd.bdate_range("2021-01-01", periods=n_days)
    X = pd.DataFrame(rng.standard_normal((n_days, n_assets)) * 0.01,
                     index=dates,
                     columns=[f"A{i}" for i in range(n_assets)])
    w = rng.dirichlet(np.ones(n_assets))
    y = pd.DataFrame(
        {"bm": X.to_numpy() @ w + rng.standard_normal(n_days) * 0.001},
        index=dates)
    rebdates = [str(d.date()) for d in dates[80::20][:3]]
    bs = BacktestService(
        data={"return_series": X, "bm_series": y},
        selection_item_builders={
            "data": SelectionItemBuilder(bibfn=bibfn_selection_data)},
        optimization_item_builders={
            "returns": OptimizationItemBuilder(bibfn=bibfn_return_series,
                                               width=60),
            "bm": OptimizationItemBuilder(bibfn=bibfn_bm_series, width=60,
                                          align=True),
            "budget": OptimizationItemBuilder(bibfn=bibfn_budget_constraint),
            "box": OptimizationItemBuilder(bibfn=bibfn_box_constraints),
        },
        optimization=LeastSquares(),
        settings={"rebdates": rebdates, "quiet": True},
    )
    findings = contracts.check_run_batch(bs)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_contracts_detect_seeded_violations():
    from porqua_tpu.analysis import contracts

    def bad(x):
        y = x.astype(jnp.float64)

        def cb(a):
            return np.asarray(a)

        z = jax.pure_callback(
            cb, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y, z, jnp.arange(4)

    closed = jax.make_jaxpr(bad)(jax.ShapeDtypeStruct((4,), jnp.float32))
    rules = {f.rule for f in contracts.check_closed_jaxpr(closed, "bad")}
    # f64 cast, callback primitive, and the f64/int64 outputs
    assert {"GC101", "GC102", "GC103"} <= rules


# ---------------------------------------------------------------------------
# runtime sanitizer
# ---------------------------------------------------------------------------

SERVE_PARAMS = porqua_tpu.SolverParams(
    max_iter=300, eps_abs=1e-4, eps_rel=1e-4, polish=False,
    check_interval=25)


def make_qp(n=6, m=2, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * n, n))
    P = A.T @ A / (2 * n) + np.eye(n)
    q = rng.standard_normal(n)
    C = np.concatenate([np.ones((1, n)), rng.standard_normal((m - 1, n))])
    return porqua_tpu.CanonicalQP.build(
        P, q, C=C, l=np.full(m, -1.0), u=np.ones(m),
        lb=np.zeros(n), ub=np.ones(n))


def test_sanitizer_transfer_guard(monkeypatch):
    monkeypatch.delenv("PORQUA_SANITIZE", raising=False)
    with sanitize.transfer_guard():  # disabled: a no-op
        jnp.sin(np.ones(3)).block_until_ready()

    monkeypatch.setenv("PORQUA_SANITIZE", "1")
    with pytest.raises(Exception, match="[Dd]isallow"):
        with sanitize.transfer_guard():
            jnp.sin(np.ones(3)).block_until_ready()  # implicit h2d
    with sanitize.transfer_guard():  # explicit device_put is allowed
        jnp.sin(jax.device_put(np.ones(3))).block_until_ready()


def test_sanitizer_zero_recompiles_after_warmup(monkeypatch):
    monkeypatch.setenv("PORQUA_SANITIZE", "1")
    sanitize.reset()
    try:
        ladder = BucketLadder(n_rungs=(8, 16), m_rungs=(4,))
        svc = SolveService(params=SERVE_PARAMS, ladder=ladder,
                           max_batch=2, max_wait_ms=1.0)
        with svc:
            compiled = svc.prewarm(make_qp())
            assert compiled >= 1
            assert svc.cache.warmed  # warmup scoped to THIS cache
            assert sanitize.compile_count() >= compiled

            # Steady state: a prewarmed-bucket solve must not compile.
            res = svc.solve(make_qp(seed=1), timeout=120)
            assert res.found
            assert sanitize.post_warmup_compiles() == 0

            # A cold bucket post-warmup is an invariant violation: the
            # sanitizer refuses the compile and the request fails loudly
            # instead of paying a mid-traffic compile stall.
            with pytest.raises(SolveError, match="compile after warmup"):
                svc.solve(make_qp(n=12, seed=2), timeout=120)
            assert sanitize.post_warmup_compiles() >= 1
            # ...but a policy violation is NOT a device fault: the
            # circuit breaker stays closed and healthy buckets keep
            # dispatching on the primary device.
            assert not svc.health.degraded
            assert svc.solve(make_qp(seed=3), timeout=120).found

            # A second service's own warmup is unaffected by the
            # first one having sealed its cache.
            svc2 = SolveService(params=SERVE_PARAMS,
                                ladder=BucketLadder(n_rungs=(8,),
                                                    m_rungs=(4,)),
                                max_batch=1, max_wait_ms=1.0)
            with svc2:
                assert svc2.prewarm(make_qp()) >= 1
                assert svc2.solve(make_qp(seed=4), timeout=120).found
    finally:
        sanitize.reset()


def test_sanitizer_counters_run_without_enforcement(monkeypatch):
    monkeypatch.delenv("PORQUA_SANITIZE", raising=False)
    sanitize.reset()
    try:
        sanitize.note_compile("probe")
        assert sanitize.compile_count() == 1
        sanitize.warmup_complete()
        sanitize.note_compile("probe")  # counted, not raised
        assert sanitize.post_warmup_compiles() == 1
        with sanitize.no_recompile():
            pass  # no compile: fine either way
    finally:
        sanitize.reset()
