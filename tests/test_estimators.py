"""Covariance and mean estimators: parity with pandas/numpy references
and batchability (the properties the reference's estimators lack)."""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from porqua_tpu.estimators.covariance import (
    Covariance,
    cov_duv,
    cov_ledoit_wolf,
    cov_linear_shrinkage,
    cov_pearson,
)
from porqua_tpu.estimators.mean import MeanEstimator, geometric_mean
from porqua_tpu.utils.psd import is_psd, nearest_psd, project_psd


@pytest.fixture
def frame(rng):
    return pd.DataFrame(
        rng.standard_normal((120, 6)) * 0.01,
        columns=[f"A{i}" for i in range(6)],
    )


def test_pearson_matches_pandas(frame):
    got = Covariance(method="pearson").estimate(frame)
    np.testing.assert_allclose(got.to_numpy(), frame.cov().to_numpy(), atol=1e-12)
    assert list(got.columns) == list(frame.columns)


def test_duv_identity(frame):
    got = Covariance(method="duv").estimate(frame)
    np.testing.assert_allclose(got.to_numpy(), np.eye(6))


def test_linear_shrinkage_ridge(frame):
    lam = 0.3
    got = Covariance(method="linear_shrinkage",
                     lambda_covmat_regularization=lam).estimate(frame)
    S = frame.cov().to_numpy()
    expected = S + lam * np.mean(np.diag(S)) * np.eye(6)
    np.testing.assert_allclose(got.to_numpy(), expected, atol=1e-12)


def test_ledoit_wolf_shrinks_toward_identity(rng):
    # Few observations, many assets: heavy shrinkage expected.
    X = jnp.asarray(rng.standard_normal((12, 10)) * 0.01)
    lw = cov_ledoit_wolf(X)
    sample = cov_pearson(X) * 11 / 12
    mu = float(jnp.trace(lw)) / 10
    off_lw = np.abs(np.asarray(lw - jnp.diag(jnp.diag(lw)))).sum()
    off_s = np.abs(np.asarray(sample - jnp.diag(jnp.diag(sample)))).sum()
    assert off_lw < off_s  # off-diagonals pulled toward 0
    assert is_psd(lw)
    assert mu > 0


def test_estimators_vmap_over_windows(rng):
    """A batch of rolling windows estimates as one op — the device path
    the reference's per-date loop cannot take."""
    X = jnp.asarray(rng.standard_normal((7, 60, 5)) * 0.01)
    batched = jax.vmap(cov_pearson)(X)
    assert batched.shape == (7, 5, 5)
    single = cov_pearson(X[3])
    np.testing.assert_allclose(np.asarray(batched[3]), np.asarray(single), atol=1e-14)


def test_geometric_mean_momentum_reversal(frame):
    n_mom, n_rev = 60, 10
    est = MeanEstimator(n_mom=n_mom, n_rev=n_rev)
    got = est.estimate(frame)
    window = frame.iloc[-n_mom:-n_rev]
    expected = np.exp(np.log1p(window).mean()) - 1
    np.testing.assert_allclose(got.to_numpy(), expected.to_numpy(), atol=1e-12)


def test_geometric_mean_scalefactor(rng):
    X = jnp.asarray(rng.standard_normal((50, 4)) * 0.01)
    mu = geometric_mean(X, scalefactor=252.0)
    ref = np.exp(np.log1p(np.asarray(X)).mean(axis=0) * 252) - 1
    np.testing.assert_allclose(np.asarray(mu), ref, atol=1e-10)


def test_psd_projection_repairs_indefinite():
    A = jnp.asarray(np.diag([1.0, -0.5, 2.0]))
    assert not bool(is_psd(A))
    fixed = project_psd(A)
    assert bool(is_psd(fixed))
    np.testing.assert_allclose(np.asarray(fixed), np.diag([1.0, 0.0, 2.0]), atol=1e-12)


def test_nearest_psd_passes_cholesky(rng):
    B = rng.standard_normal((8, 8))
    A = jnp.asarray(B + B.T)  # indefinite symmetric
    fixed = nearest_psd(A)
    np.linalg.cholesky(np.asarray(fixed))  # must not raise


def test_covariance_auto_repair(rng):
    """check_positive_definite repairs a constructed non-PSD input."""
    cov = Covariance(method="pearson")
    X = rng.standard_normal((4, 6)) * 0.01  # T < N: singular but PSD
    out = cov.estimate_array(jnp.asarray(X))
    assert bool(is_psd(out, tol=1e-10))


def test_covariance_factor_reproduces_estimate():
    """Sigma == F'F + diag(d) for every Gram-structured method — the
    factor form MeanVariance assembles P from."""
    rng = np.random.default_rng(3)
    X = rng.standard_normal((60, 10)) * 0.01
    for method, kwargs in [
        ("pearson", {}),
        ("duv", {}),
        ("linear_shrinkage", {"lambda_covmat_regularization": 0.2}),
        ("ledoit_wolf", {}),
    ]:
        cov = Covariance(method=method, **kwargs)
        fac = cov.factor(X)
        assert fac is not None, method
        F, d = fac
        sigma_fac = F.T @ F + np.diag(d)
        sigma = np.asarray(cov.estimate_array(jnp.asarray(X)))
        np.testing.assert_allclose(sigma_fac, sigma, atol=1e-10,
                                   err_msg=method)
