"""Regression-workflow tests (reference ``example/ml.ipynb`` parity).

OLS/PCA validated against independent numpy/sklearn references; the
boosted grid search exercises the notebook cells 10-11 contract.
"""

import numpy as np
import pytest

from porqua_tpu.models.regression import (
    OLS,
    PCA,
    PCAOLS,
    boosted_regression,
    calculate_mape,
    calculate_rmse,
)


@pytest.fixture(scope="module")
def panel():
    """Linear factor panel: y = X beta + noise."""
    rng = np.random.default_rng(21)
    n, d = 400, 8
    X = rng.standard_normal((n, d))
    beta = rng.standard_normal(d)
    y = X @ beta + 0.05 * rng.standard_normal(n)
    return X, y, beta


def test_ols_matches_numpy_lstsq(panel):
    X, y, beta = panel
    model = OLS().fit(X, y)
    ref, *_ = np.linalg.lstsq(X, y, rcond=None)
    np.testing.assert_allclose(model.coef_, ref, atol=1e-4)
    pred = model.predict(X)
    assert calculate_rmse(y, pred) < 0.06
    assert calculate_mape(y, pred) < 100.0


def test_ols_with_constant():
    rng = np.random.default_rng(3)
    X = rng.standard_normal((200, 2))
    y = 3.0 + X @ np.array([1.0, -2.0])
    model = OLS(add_constant=True).fit(X, y)
    assert model.coef_[0] == pytest.approx(3.0, abs=1e-3)
    np.testing.assert_allclose(model.predict(X), y, atol=1e-3)


def test_pca_matches_sklearn(panel):
    sk_dec = pytest.importorskip("sklearn.decomposition")
    sk_pre = pytest.importorskip("sklearn.preprocessing")
    X, *_ = panel
    ours = PCA(n_components=4).fit(X)
    Z = sk_pre.StandardScaler().fit_transform(X)
    theirs = sk_dec.PCA(n_components=4).fit(Z)
    np.testing.assert_allclose(
        ours.explained_variance_ratio_[:4],
        theirs.explained_variance_ratio_, atol=1e-4)
    # components match up to sign
    ot = ours.transform(X)
    tt = theirs.transform(Z)
    for j in range(4):
        c = np.corrcoef(ot[:, j], tt[:, j])[0, 1]
        assert abs(c) > 0.999


def test_pca_ols_pipeline_predicts(panel):
    X, y, _ = panel
    # full-rank PCA keeps all signal: with an intercept to absorb the
    # centering, pipeline ~= plain OLS
    model = PCAOLS(n_components=8, add_constant=True).fit(X, y)
    assert calculate_rmse(y, model.predict(X)) < 0.06
    # truncated PCA still beats the mean-only predictor
    trunc = PCAOLS(n_components=3).fit(X, y)
    assert calculate_rmse(y, trunc.predict(X)) < calculate_rmse(y, np.full_like(y, y.mean()))


def test_boosted_regression_grid_search(panel):
    X, y, _ = panel
    est, params, cv_rmse = boosted_regression(
        X[:300], y[:300],
        param_grid={"max_depth": [3], "max_iter": [50, 100]}, cv=2)
    assert set(params) == {"max_depth", "max_iter"}
    assert cv_rmse > 0
    pred = est.predict(X[300:])
    # learns real structure on held-out data
    assert calculate_rmse(y[300:], pred) < np.std(y[300:])


def test_show_result_reports_and_returns_figure(panel, capsys):
    """Reference ``helper_functions.py:119-129`` parity: RMSE/MAPE are
    printed and a figure of prediction vs actual is produced (returned,
    not shown — headless environments)."""
    import pandas as pd

    pytest.importorskip("matplotlib")
    from porqua_tpu.utils.helpers import show_result

    X, y, _ = panel
    pred = OLS().fit(X, y).predict(X)
    fig = show_result(pd.Series(pred), y, y, method="OLS")
    out = capsys.readouterr().out
    assert "RMSE of OLS" in out and "MAPE of OLS" in out
    assert fig is not None and fig.axes[0].get_title() == "OLS"
