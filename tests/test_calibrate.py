"""Closed-loop route calibration (porqua_tpu.obs.calibrate).

Host-side contracts only — no compiles, no wall-clock sleeps: every
time-dependent path steps a FaultClock. Pins the staged promotion
state machine (idle -> canary dwell -> versioned promote -> guard ->
settle), the poisoned-evidence rejection gate, the guard-breach
auto-rollback (version bumped, NEVER reused; cooldown refuses an
immediate re-candidate; exactly one ``route_rollback`` event), the
audit chain replaying to the active table, and the deliberate
tenant-blindness of the evidence pool (the calibrator can never build
a per-tenant route table).
"""

import dataclasses
import math

import pytest

from porqua_tpu.obs.calibrate import (CALIBRATION_AUDIT_SOURCE,
                                      Calibrator, replay_audit)
from porqua_tpu.obs.harvest import HarvestSink, solve_record
from porqua_tpu.qp.solve import SolverParams
from porqua_tpu.resilience.faults import FaultClock
from porqua_tpu.serve import Bucket
from porqua_tpu.serve.routing import SolverRouter

PARAMS = SolverParams(max_iter=500, eps_abs=1e-5, eps_rel=1e-5,
                      polish=False, check_interval=25)
EPS = float(PARAMS.eps_abs)
CELL = f"8x4@{EPS:.0e}"


def _serve_rec(method, *, bucket="8x4", iters=40, solve_s=4e-3,
               status=1, obj=0.1, tenant=None):
    p = dataclasses.replace(PARAMS, method=method)
    return solve_record("serve", 6, 2, status, iters, 1e-6, 1e-6, obj,
                        params=p, bucket=bucket, solve_s=solve_s,
                        tenant=tenant)


def _shadow_rec(method="pdhg", *, shadow_of="admm", bucket="8x4",
                iters=12, solve_s=1e-5, obj=0.1, agree=True,
                delta_iters=-28, delta_solve_s=-4e-3, tenant=None):
    p = dataclasses.replace(PARAMS, method=method)
    rec = solve_record("serve.shadow", 6, 2, 1, iters, 1e-6, 1e-6, obj,
                       params=p, bucket=bucket, solve_s=solve_s,
                       tenant=tenant, shadow_of=shadow_of,
                       delta_iters=delta_iters, delta_obj=0.0,
                       agree=agree)
    if delta_solve_s is not None:
        rec["delta_solve_s"] = delta_solve_s
    return rec


def _offer_evidence(cal, n=6, tenant=None):
    # Paired evidence: the incumbent (admm) served, pdhg shadowed it
    # and won every comparison — the minimal promotable stream.
    for _ in range(n):
        assert cal.observe(_serve_rec("admm", tenant=tenant))
        assert cal.observe(_shadow_rec(tenant=tenant))


class _Events:
    def __init__(self):
        self.emitted = []

    def emit(self, kind, severity, **fields):
        self.emitted.append((kind, severity, fields))

    def kinds(self, kind):
        return [e for e in self.emitted if e[0] == kind]


class _Anomaly:
    def __init__(self):
        self.fired = 0

    def counters(self):
        return {"anomalies_fired": self.fired}


def _mk(clk, **kw):
    router = SolverRouter(PARAMS)
    sink = HarvestSink()
    events = _Events()
    anomaly = _Anomaly()
    kw.setdefault("min_interval_s", 0.0)
    kw.setdefault("min_samples", 4)
    kw.setdefault("win_rate", 0.6)
    kw.setdefault("canary_dwell_s", 5.0)
    kw.setdefault("guard_window_s", 10.0)
    cal = Calibrator(router=router, harvest=sink, events=events,
                     anomaly=anomaly, clock=clk, **kw)
    return cal, router, sink, events, anomaly


# ---------------------------------------------------------------------------
# validation + ingestion gates
# ---------------------------------------------------------------------------

def test_calibrator_validation():
    with pytest.raises(ValueError, match="win_rate"):
        Calibrator(win_rate=1.5)
    with pytest.raises(ValueError, match="min_samples"):
        Calibrator(min_samples=0)
    with pytest.raises(ValueError, match="max_records_per_cell"):
        Calibrator(max_records_per_cell=0)


def test_observe_rejects_untrusted_evidence():
    """The poison gate: records a corrupted feed produces (non-finite
    objective, NaN deltas, missing cell coordinates, unknown backend)
    are rejected — counted, never folded, never raised."""
    cal = Calibrator(clock=FaultClock())
    no_bucket = _serve_rec("admm")
    del no_bucket["bucket"]                    # no cell coordinates
    bad = [
        _serve_rec("admm", obj=float("nan")),
        no_bucket,
        _shadow_rec(delta_iters=None),
        _shadow_rec(delta_solve_s=float("inf")),
    ]
    rec = _serve_rec("admm")
    rec["solver"] = "qpth"
    bad.append(rec)
    for r in bad:
        assert cal.observe(r) is False
    assert cal.observe(_serve_rec("admm")) is True
    c = cal.counters()
    assert c["calibration_rejected"] == len(bad)
    assert c["calibration_observed"] == 1
    assert cal.evidence()[CELL]["per_method"]["admm"]["count"] == 1


def test_maybe_tick_clock_gate():
    clk = FaultClock()
    cal, _, _, _, _ = _mk(clk, min_interval_s=5.0)
    assert cal.maybe_tick() is False          # inside the interval
    clk.advance(6.0)
    assert cal.maybe_tick() is True
    assert cal.maybe_tick() is False          # gate re-arms
    assert cal.counters()["calibration_ticks"] == 1


# ---------------------------------------------------------------------------
# staged promotion
# ---------------------------------------------------------------------------

def test_promotion_state_machine_and_audit_replay():
    clk = FaultClock()
    cal, router, sink, events, _ = _mk(clk)
    assert router.table_version == 0
    assert router.route(Bucket(8, 4, None)) == "admm"

    _offer_evidence(cal)
    cal.tick()                                 # idle -> canary
    assert cal.status()["state"] == "canary"
    assert cal.status()["candidate_cells"] == [CELL]
    assert router.table_version == 0           # nothing swapped yet

    clk.advance(6.0)
    cal.tick()                                 # dwell held -> promote
    assert cal.status()["state"] == "guard"
    assert router.table_version == 1
    assert router.snapshot()["table"] == {CELL: "pdhg"}
    assert router.route(Bucket(8, 4, None)) == "pdhg"

    clk.advance(11.0)
    cal.tick()                                 # guard expires -> settle
    c = cal.counters()
    assert cal.status()["state"] == "idle"
    assert c["calibration_promotions"] == 1
    assert c["calibration_rollbacks"] == 0
    assert c["calibration_settled"] == 1

    # Every transition emitted route_reseed; the promote one carries
    # the full evidence diff (per-method stats + the shadow win rate
    # that gated it).
    states = [e[2]["state"] for e in events.kinds("route_reseed")]
    assert states == ["candidate", "promoted", "settled"]
    diff = events.kinds("route_reseed")[1][2]["diff"][CELL]
    assert diff["old"] == "admm" and diff["new"] == "pdhg"
    assert diff["evidence"]["shadow"]["win_rate"] == 1.0

    # Audit chain: landed in the warehouse AND replays to the active
    # router state from the records alone.
    audits = [r for r in sink.buffered()
              if r["source"] == CALIBRATION_AUDIT_SOURCE]
    assert [r["action"] for r in audits] == ["candidate", "promote"]
    table, version = replay_audit(sink.buffered())
    assert table == router.snapshot()["table"]
    assert version == router.table_version == 1

    # Gauges track the plane.
    g = cal.gauges()
    assert g["calibration_route_table_version"] == 1.0
    assert g["calibration_promotions_total"] == 1.0
    assert g["calibration_state"] == 0.0       # settled back to idle


def test_insufficient_shadow_evidence_never_candidates():
    """min_samples gates BOTH the per-backend evidence pool and the
    winner's shadow comparisons — serve records alone can't promote."""
    clk = FaultClock()
    cal, router, _, _, _ = _mk(clk, min_samples=4)
    for _ in range(6):
        cal.observe(_serve_rec("admm"))
        cal.observe(_serve_rec("pdhg", iters=12, solve_s=1e-5))
    cal.tick()
    assert cal.status()["state"] == "idle"
    assert cal.counters()["calibration_candidates"] == 0
    assert router.table_version == 0


# ---------------------------------------------------------------------------
# guard breach -> rollback
# ---------------------------------------------------------------------------

def test_rollback_bumps_version_never_reuses():
    """The satellite regression: a guard breach reverts to the PRIOR
    table under a NEW version (1 -> 2, never back to 0), fires exactly
    one route_rollback event, drops the discredited evidence, and the
    cooldown refuses an immediate re-candidate. The audit chain —
    which only the calibrator wrote (cold-start flow; a
    seed_from_aggregate bootstrap bumps the version with no audit
    record, so chain-replay == router-state holds only here) — replays
    to the active table."""
    clk = FaultClock()
    cal, router, sink, events, anomaly = _mk(clk)

    _offer_evidence(cal)
    cal.tick()
    clk.advance(6.0)
    cal.tick()
    assert router.table_version == 1
    assert cal.status()["state"] == "guard"

    # Policy-induced drift inside the guard window: the anomaly
    # detector fires -> breach -> auto-rollback.
    anomaly.fired += 1
    clk.advance(1.0)
    cal.tick()
    assert cal.status()["state"] == "idle"
    assert cal.counters()["calibration_rollbacks"] == 1
    assert router.table_version == 2           # bumped, NOT back to 0
    assert router.snapshot()["table"] == {}    # prior (empty) content
    assert router.route(Bucket(8, 4, None)) == "admm"

    rollbacks = events.kinds("route_rollback")
    assert len(rollbacks) == 1
    assert rollbacks[0][1] == "error"
    assert "anomaly_fired +1" in rollbacks[0][2]["reason"]

    # Discredited evidence was dropped; fresh evidence inside the
    # cooldown must not re-candidate.
    assert cal.evidence() == {}
    _offer_evidence(cal)
    clk.advance(1.0)
    cal.tick()
    assert cal.status()["state"] == "idle"
    assert cal.counters()["calibration_candidates"] == 1
    assert cal.status()["cooldown_remaining_s"] > 0

    # After the cooldown the same evidence may earn its way back.
    clk.advance(cal.cooldown_s + 1.0)
    cal.tick()
    assert cal.counters()["calibration_candidates"] == 2

    # The audit chain replays to the post-rollback state.
    table, version = replay_audit(sink.buffered())
    assert table == {} and version == 2
    assert (table, version) == (router.snapshot()["table"],
                                router.table_version)


def test_replay_audit_rejects_nonmonotonic_versions():
    def audit(action, version):
        return {"v": 1, "source": CALIBRATION_AUDIT_SOURCE, "t": 0.0,
                "action": action, "table_version": version,
                "table": {CELL: "pdhg"}}

    table, version = replay_audit(
        [audit("promote", 1), {"source": "serve"}, audit("rollback", 2)])
    assert version == 2
    with pytest.raises(ValueError, match="not monotonic"):
        replay_audit([audit("promote", 2), audit("rollback", 2)])


# ---------------------------------------------------------------------------
# tenancy
# ---------------------------------------------------------------------------

def test_evidence_pools_across_tenants():
    """serve.shadow records carry tenant attribution, but the
    calibrator deliberately ignores it: evidence for one (bucket, eps)
    cell pools across tenants — 3 samples from each of two tenants
    clear a min_samples=4 gate neither clears alone — and the
    candidate table is global (cell-keyed, no tenant axis), so the
    calibrator can never build a per-tenant route table."""
    clk = FaultClock()
    cal, router, _, _, _ = _mk(clk, min_samples=4)
    for tenant in ("fund-a", "fund-b"):
        _offer_evidence(cal, n=3, tenant=tenant)

    ev = cal.evidence()
    assert list(ev) == [CELL]                  # one pooled cell
    assert ev[CELL]["per_method"]["admm"]["count"] == 6
    assert ev[CELL]["shadow"]["pdhg"]["samples"] == 6

    cal.tick()
    assert cal.status()["state"] == "canary"
    clk.advance(6.0)
    cal.tick()
    assert router.snapshot()["table"] == {CELL: "pdhg"}
    assert not any("fund" in k for k in router.snapshot()["table"])


# ---------------------------------------------------------------------------
# plane resilience
# ---------------------------------------------------------------------------

def test_tick_errors_never_propagate():
    """A broken calibration plane must not fail served traffic:
    maybe_tick swallows and counts, never raises."""
    class _BadRouter:
        default_method = "admm"
        table_version = 0

        def reset_shadow_budget(self):
            raise RuntimeError("boom")

    clk = FaultClock()
    cal = Calibrator(router=_BadRouter(), min_interval_s=0.0,
                     clock=clk)
    clk.advance(1.0)
    assert cal.maybe_tick() is False
    assert cal.counters()["calibration_tick_errors"] == 1
    with pytest.raises(RuntimeError):
        cal.tick()                             # gate-free entry raises


# ---------------------------------------------------------------------------
# three-backend generalization (NAPG as third contender)
# ---------------------------------------------------------------------------

def test_three_contender_cell_promotes_best_of_three():
    """A cell where all three backends matured scores N-ary: NAPG's
    lower latency beats pdhg AND the admm incumbent, and the promoted
    table routes the cell to napg."""
    clk = FaultClock()
    cal, router, _, events, _ = _mk(clk)
    for _ in range(6):
        assert cal.observe(_serve_rec("admm", iters=60, solve_s=4e-3))
        assert cal.observe(_shadow_rec("pdhg", iters=30, solve_s=2e-3,
                                       delta_iters=-30,
                                       delta_solve_s=-2e-3))
        assert cal.observe(_shadow_rec("napg", iters=12, solve_s=5e-4,
                                       delta_iters=-48,
                                       delta_solve_s=-3.5e-3))
    cal.tick()                                 # idle -> canary
    assert cal.status()["state"] == "canary"
    clk.advance(6.0)
    cal.tick()                                 # dwell held -> promote
    assert router.snapshot()["table"] == {CELL: "napg"}
    diff = events.kinds("route_reseed")[1][2]["diff"][CELL]
    assert diff["old"] == "admm" and diff["new"] == "napg"
    assert set(diff["evidence"]["per_method"]) == {"admm", "pdhg",
                                                   "napg"}


def test_thin_third_stream_does_not_block_comparison():
    """A backend below min_samples simply is not a contender yet: two
    matured backends still compare (and promote) while the third's
    evidence stream is warming up — the three-way generalization must
    not regress the two-way promotion latency."""
    clk = FaultClock()
    cal, router, _, _, _ = _mk(clk, min_samples=4)
    for _ in range(6):
        assert cal.observe(_serve_rec("admm"))
        assert cal.observe(_shadow_rec("pdhg"))
    # One napg observation: matured nowhere near min_samples.
    assert cal.observe(_shadow_rec("napg", iters=500, solve_s=1e-2,
                                   delta_iters=460,
                                   delta_solve_s=6e-3))
    cal.tick()
    assert cal.status()["state"] == "canary", cal.status()
    clk.advance(6.0)
    cal.tick()
    assert router.snapshot()["table"] == {CELL: "pdhg"}
