"""Post-lowering HLO lint plane (GC201-GC206).

Seeded-violation coverage for every rule in
:mod:`porqua_tpu.analysis.hlolint` — each test plants one defect in
synthetic optimized-HLO text and asserts the rule id AND the anchor
(the ``<hlo:program>`` virtual path + the HLO line) — plus the parser,
the suppression table, the committed ``HLO_BASELINE.json`` artifact
(clean at zero suppressions, one entry per harvested entry point), the
``run_checks.py --stats`` schema pin, and the bench-gate hlo rule
class on payload fixtures. Everything here is synthetic text: the only
AOT compile lives in the ``slow``-marked end-to-end harvest test.
"""

import json
import os
import subprocess
import sys

import pytest

from porqua_tpu.analysis import hlolint
from porqua_tpu.analysis.hlolint import (
    Finding, LintConfig, apply_suppressions, check_dtype_drift,
    check_fusion_miss, check_layout_churn, check_padding_waste,
    check_redundant_materialization, check_temp_peak, hlo_path,
    lint_module, parse_hlo, path_program, shape_bytes, shape_dtypes)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

_MODULE = """\
HloModule jit_step, is_scheduled=true, entry_computation_layout={(f32[4,16]{1,0})->(f32[4,16]{1,0}, s32[])}

%fused_computation (param_0: f32[4,16], param_1: f32[4,16]) -> f32[4,16] {
  %param_0 = f32[4,16]{1,0} parameter(0)
  %param_1 = f32[4,16]{1,0} parameter(1)
  %mul = f32[4,16]{1,0} multiply(%param_0, %param_1)
  ROOT %add = f32[4,16]{1,0} add(%mul, %param_1)
}

%region_sum (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

ENTRY %main (p0: f32[4,16], p1: f32[4,16]) -> (f32[4,16], s32[]) {
  %p0 = f32[4,16]{1,0} parameter(0)
  %p1 = f32[4,16]{1,0} parameter(1)
  %zero = f32[] constant(0)
  %fusion = f32[4,16]{1,0} fusion(%p0, %p1), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(step)/mul" source_file="x.py" source_line=7}
  %red = f32[4]{0} reduce(%fusion, %zero), dimensions={1}, to_apply=%region_sum
  %iota = s32[] constant(3)
  ROOT %tuple = (f32[4,16]{1,0}, s32[]) tuple(%fusion, %iota)
}
"""


def test_parser_structure():
    mod = parse_hlo(_MODULE)
    assert mod.name == "jit_step"
    assert set(mod.computations) == {"fused_computation", "region_sum",
                                     "main"}
    assert mod.entry is not None and mod.entry.name == "main"
    assert mod.entry.params == [("p0", "f32[4,16]"), ("p1", "f32[4,16]")]
    fusion = mod.entry.by_name["fusion"]
    assert fusion.opcode == "fusion"
    assert fusion.operands == ("p0", "p1")
    assert fusion.called == ("fused_computation",)
    assert fusion.line == 20
    red = mod.entry.by_name["red"]
    assert red.called == ("region_sum",)
    root = mod.entry.root
    assert root is not None and root.name == "tuple" and root.is_root
    # Fusion bodies vs scheduled computations: the fused body and the
    # reducer lambda are not scheduled; ENTRY is.
    assert set(mod.fusion_bodies()) == {"fused_computation"}
    assert [c.name for c in mod.scheduled_computations()] == ["main"]


def test_shape_arithmetic():
    assert shape_bytes("f32[4,16]{1,0}") == 256
    assert shape_bytes("f32[]") == 4
    assert shape_bytes("pred[8]") == 8
    assert shape_bytes("(f32[4,16]{1,0}, s32[])") == 260
    assert shape_bytes("f64[2,3]") == 48
    assert shape_dtypes("(f32[4]{0}, s32[], f64[2]{0})") == {
        "f32", "s32", "f64"}


def test_hlo_path_round_trip():
    assert hlo_path("solve_batch[pdhg]") == "<hlo:solve_batch[pdhg]>"
    assert path_program("<hlo:solve_batch[pdhg]>") == "solve_batch[pdhg]"
    assert path_program("porqua_tpu/qp/admm.py") is None


# ---------------------------------------------------------------------------
# GC201 — fusion miss
# ---------------------------------------------------------------------------

def _elementwise_chain(n: int) -> str:
    return f"""\
HloModule seed, is_scheduled=true

ENTRY %main (p0: f32[{n},{n}], p1: f32[{n},{n}]) -> f32[{n},{n}] {{
  %p0 = f32[{n},{n}]{{1,0}} parameter(0)
  %p1 = f32[{n},{n}]{{1,0}} parameter(1)
  %mul = f32[{n},{n}]{{1,0}} multiply(%p0, %p1)
  ROOT %add = f32[{n},{n}]{{1,0}} add(%mul, %p0)
}}
"""


def test_gc201_seeded_fusion_miss():
    mod = parse_hlo(_elementwise_chain(256))  # 256 KiB intermediate
    found = check_fusion_miss(mod, "seedprog")
    assert len(found) == 1
    f = found[0]
    assert f.rule == "GC201"
    assert f.path == "<hlo:seedprog>"
    assert f.line == 6  # the producer %mul
    assert "multiply -> add" in f.message and "262144 B" in f.message


def test_gc201_below_ridge_is_clean():
    # A 4 KiB intermediate is latency noise, not a fusion target.
    mod = parse_hlo(_elementwise_chain(32))
    assert check_fusion_miss(mod, "p") == []


def test_gc201_ranked_widest_first():
    text = """\
HloModule seed, is_scheduled=true

ENTRY %main (p0: f32[256,256], p1: f32[512,512]) -> f32[512,512] {
  %p0 = f32[256,256]{1,0} parameter(0)
  %p1 = f32[512,512]{1,0} parameter(1)
  %small = f32[256,256]{1,0} multiply(%p0, %p0)
  %snext = f32[256,256]{1,0} add(%small, %p0)
  %big = f32[512,512]{1,0} multiply(%p1, %p1)
  ROOT %bnext = f32[512,512]{1,0} add(%big, %p1)
}
"""
    found = check_fusion_miss(parse_hlo(text), "p")
    assert [f.line for f in found] == [8, 6]  # %big outranks %small


# ---------------------------------------------------------------------------
# GC202 — redundant materialization
# ---------------------------------------------------------------------------

def _twin_fusions(operands2: str = "%p0, %p1") -> str:
    return f"""\
HloModule seed, is_scheduled=true

%fc.1 (a.1: f32[64,64], b.1: f32[64,64]) -> f32[64,64] {{
  %a.1 = f32[64,64]{{1,0}} parameter(0)
  %b.1 = f32[64,64]{{1,0}} parameter(1)
  %m.1 = f32[64,64]{{1,0}} multiply(%a.1, %b.1)
  ROOT %s.1 = f32[64,64]{{1,0}} subtract(%m.1, %b.1)
}}

%fc.2 (a.2: f32[64,64], b.2: f32[64,64]) -> f32[64,64] {{
  %a.2 = f32[64,64]{{1,0}} parameter(0)
  %b.2 = f32[64,64]{{1,0}} parameter(1)
  %m.2 = f32[64,64]{{1,0}} multiply(%a.2, %b.2)
  ROOT %s.2 = f32[64,64]{{1,0}} subtract(%m.2, %b.2)
}}

ENTRY %main (p0: f32[64,64], p1: f32[64,64]) -> f32[64,64] {{
  %p0 = f32[64,64]{{1,0}} parameter(0)
  %p1 = f32[64,64]{{1,0}} parameter(1)
  %f1 = f32[64,64]{{1,0}} fusion(%p0, %p1), kind=kLoop, calls=%fc.1
  %f2 = f32[64,64]{{1,0}} fusion({operands2}), kind=kLoop, calls=%fc.2
  ROOT %o = f32[64,64]{{1,0}} add(%f1, %f2)
}}
"""


def test_gc202_seeded_twin_call_sites():
    found = check_redundant_materialization(
        parse_hlo(_twin_fusions()), "seedprog")
    assert len(found) == 1
    f = found[0]
    assert f.rule == "GC202" and f.path == "<hlo:seedprog>"
    assert f.line == 21  # the second call site %f2
    assert "f2" in f.message and "f1" in f.message


def test_gc202_distinct_operands_are_clean():
    # XLA clones one fusion body per call site by design (unrolled
    # segment steps): identical bodies over DIFFERENT operands
    # recompute nothing and must not fire.
    found = check_redundant_materialization(
        parse_hlo(_twin_fusions(operands2="%p1, %p0")), "p")
    assert found == []


def test_gc202_byte_floor():
    # The same twins under the floor are XLA-CSE noise (the committed
    # tree carries one 48 B 0/D pair in ruiz scaling — README triage).
    found = check_redundant_materialization(
        parse_hlo(_twin_fusions()), "p", min_bytes=1 << 20)
    assert found == []


def test_gc202_duplicate_dot():
    text = """\
HloModule seed, is_scheduled=true

ENTRY %main (p0: f32[32,32], p1: f32[32,32]) -> f32[32,32] {
  %p0 = f32[32,32]{1,0} parameter(0)
  %p1 = f32[32,32]{1,0} parameter(1)
  %d1 = f32[32,32]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %d2 = f32[32,32]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %o = f32[32,32]{1,0} add(%d1, %d2)
}
"""
    found = check_redundant_materialization(parse_hlo(text), "p")
    assert len(found) == 1
    assert found[0].rule == "GC202" and found[0].line == 7
    assert "dot" in found[0].message


# ---------------------------------------------------------------------------
# GC203 — layout churn
# ---------------------------------------------------------------------------

def test_gc203_seeded_churn():
    text = """\
HloModule seed, is_scheduled=true

ENTRY %main (p0: f32[128,128]) -> f32[128,128] {
  %p0 = f32[128,128]{1,0} parameter(0)
  %t = f32[128,128]{0,1} transpose(%p0), dimensions={1,0}
  ROOT %c = f32[128,128]{1,0} copy(%t)
}
"""
    found = check_layout_churn(parse_hlo(text), "seedprog")
    assert len(found) == 1
    f = found[0]
    assert f.rule == "GC203" and f.path == "<hlo:seedprog>"
    assert f.line == 6 and "transpose" in f.message


def test_gc203_single_move_and_bitcast_are_clean():
    text = """\
HloModule seed, is_scheduled=true

ENTRY %main (p0: f32[128,128]) -> f32[128,128] {
  %p0 = f32[128,128]{1,0} parameter(0)
  %b = f32[16384]{0} bitcast(%p0)
  %t = f32[128,128]{0,1} transpose(%p0), dimensions={1,0}
  ROOT %a = f32[128,128]{0,1} add(%t, %t)
}
"""
    assert check_layout_churn(parse_hlo(text), "p") == []


# ---------------------------------------------------------------------------
# GC204 — padding waste
# ---------------------------------------------------------------------------

def test_gc204_seeded_over_budget():
    found = check_padding_waste("bucket_ladder[512x8]",
                                natural_bytes=1000.0,
                                padded_bytes=10000.0, budget=0.25,
                                bucket="512x8", line=5)
    assert len(found) == 1
    f = found[0]
    assert f.rule == "GC204" and f.path == "<hlo:bucket_ladder[512x8]>"
    assert f.line == 5 and "0.900" in f.message and "512x8" in f.message


def test_gc204_within_budget_is_clean():
    assert check_padding_waste("b", natural_bytes=9000.0,
                               padded_bytes=10000.0, budget=0.25) == []
    # Degenerate inputs check nothing rather than dividing by zero.
    assert check_padding_waste("b", natural_bytes=10.0,
                               padded_bytes=0.0) == []


def test_gc204_module_form_reads_entry_params():
    mod = parse_hlo(_elementwise_chain(64))
    # Two 16 KiB params = 32 KiB padded; a 1 KiB natural payload is
    # ~97% dead.
    found = check_padding_waste("p", natural_bytes=1024.0, module=mod,
                                budget=0.5)
    assert len(found) == 1 and found[0].line == mod.entry.line


# ---------------------------------------------------------------------------
# GC205 — temporary-peak budget
# ---------------------------------------------------------------------------

def test_gc205_seeded_over_budget():
    found = check_temp_peak("seedprog", peak_bytes=2.0e6,
                            budget_bytes=1.5e6, line=2)
    assert len(found) == 1
    f = found[0]
    assert f.rule == "GC205" and f.path == "<hlo:seedprog>"
    assert f.line == 2
    assert "2000000" in f.message and "1500000" in f.message


def test_gc205_absent_measurement_checks_nothing():
    assert check_temp_peak("p", None, 1.0e6) == []
    assert check_temp_peak("p", 1.0e6, None) == []
    assert check_temp_peak("p", 1.0e6, 1.0e6) == []


# ---------------------------------------------------------------------------
# GC206 — post-lowering dtype drift
# ---------------------------------------------------------------------------

_WIDE = """\
HloModule seed, is_scheduled=true

ENTRY %main (p0: f32[32,32]) -> f32[32,32] {
  %p0 = f32[32,32]{1,0} parameter(0)
  %wide = f64[32,32]{1,0} convert(%p0)
  %w2 = f64[32,32]{1,0} convert(%p0)
  ROOT %narrow = f32[32,32]{1,0} convert(%wide)
}
"""


def test_gc206_seeded_drift():
    found = check_dtype_drift(parse_hlo(_WIDE), "seedprog")
    # One finding per (computation, opcode): both converts collapse.
    assert len(found) == 1
    f = found[0]
    assert f.rule == "GC206" and f.path == "<hlo:seedprog>"
    assert f.line == 5 and "f64" in f.message


def test_gc206_respects_float_policy():
    assert check_dtype_drift(parse_hlo(_WIDE), "p",
                             expect_float="f64") == []


# ---------------------------------------------------------------------------
# orchestration: lint_module, rule filter, suppressions
# ---------------------------------------------------------------------------

def test_lint_module_clean_tree_shape():
    # A well-fused module with a single call site per body: clean.
    mod = parse_hlo(_twin_fusions())
    clean = _twin_fusions().replace(
        "%f2 = f32[64,64]{1,0} fusion(%p0, %p1), kind=kLoop, calls=%fc.2",
        "%f2 = f32[64,64]{1,0} fusion(%p1, %p0), kind=kLoop, calls=%fc.2")
    assert lint_module(parse_hlo(clean), "p") == []
    # The seeded one fires exactly GC202; the rules filter can turn it
    # off without touching the others.
    assert [f.rule for f in lint_module(mod, "p")] == ["GC202"]
    assert lint_module(mod, "p", rules=["GC201", "GC206"]) == []


def test_lint_config_thresholds_flow_through():
    cfg = LintConfig(dup_min_bytes=1 << 20)
    assert lint_module(parse_hlo(_twin_fusions()), "p", config=cfg) == []


def test_suppressions_require_reason():
    findings = [Finding("GC202", hlo_path("a"), 1, 1, "x"),
                Finding("GC202", hlo_path("b"), 2, 1, "y"),
                Finding("GC205", hlo_path("a"), 3, 1, "z")]
    kept, counts = apply_suppressions(findings, [
        {"program": "a", "rule": "GC202", "reason": "known twin"},
        {"program": "a", "rule": "GC205"},  # reasonless: ignored
    ])
    assert counts == {"GC202": 1}
    assert [(f.rule, path_program(f.path)) for f in kept] == [
        ("GC202", "b"), ("GC205", "a")]
    # Wildcard program suppresses the rule everywhere.
    kept2, counts2 = apply_suppressions(findings, [
        {"program": "*", "rule": "GC202", "reason": "sweep"}])
    assert counts2 == {"GC202": 2} and [f.rule for f in kept2] == ["GC205"]


# ---------------------------------------------------------------------------
# the committed baseline artifact
# ---------------------------------------------------------------------------

def test_committed_baseline_is_clean():
    """The shipped HLO_BASELINE.json: schema-pinned, one entry per
    entry-point program, zero finding floors, zero suppressions, and a
    budget for every padding cell — the 'full tree scan committed
    clean at zero suppressions' bar."""
    from porqua_tpu.analysis import hlo

    path = os.path.join(_ROOT, "HLO_BASELINE.json")
    assert os.path.exists(path), "HLO_BASELINE.json must be committed"
    with open(path) as f:
        baseline = json.load(f)
    assert baseline["schema"] == hlo.BASELINE_SCHEMA_VERSION
    assert baseline["suppressions"] == []
    programs = baseline["programs"]
    expected = {label for label, _, _ in hlo.entry_point_programs()}
    assert set(programs) == expected
    for label, entry in programs.items():
        assert entry["findings_by_rule"] == {}, (label, entry)
        assert entry["fingerprint"], label
        assert entry["peak_budget"] is None or (
            entry["peak_budget"] > entry["peak_bytes"]), label
    cells = baseline["padding"]["cells"]
    budgets = baseline["padding"]["budgets"]
    assert {c["bucket"] for c in cells} == set(budgets)
    for c in cells:
        assert budgets[c["bucket"]] > c["share"], c
    # The committed budgets hold against the CURRENT ladder arithmetic
    # (a ladder change that worsens a cell must fail this).
    from porqua_tpu.analysis.hlo import bucket_padding_cells, padding_findings
    assert padding_findings(bucket_padding_cells(),
                            budgets=budgets) == []


@pytest.mark.slow
def test_end_to_end_harvest_single_program():
    """One real AOT compile through the whole plane: harvest ->
    fingerprint -> lint clean against the committed baseline."""
    from porqua_tpu.analysis import hlo

    baseline = hlo.load_baseline()
    assert baseline is not None
    programs = hlo.harvest_entry_points(labels=["tracking_step"])
    assert len(programs) == 1
    hp = programs[0]
    assert hp.hlo_text and hp.fingerprint
    assert hp.record["kind"] == "hlolint"
    stats: dict = {}
    findings = hlo.lint_harvest(programs, baseline=baseline,
                                include_padding=False, stats_out=stats)
    assert findings == [], [f.format() for f in findings]
    assert stats["hlo_programs"] == 1
    diff = hlo.compare_fingerprints(baseline, programs)
    assert diff["flipped"] == [], diff


# ---------------------------------------------------------------------------
# run_checks --stats schema pin + bench_gate hlo rules
# ---------------------------------------------------------------------------

def test_run_checks_stats_schema_v2(tmp_path):
    """The --stats JSON contract is schema 2: findings_by_rule spans
    every plane (recounted over the final finding list), and the
    suppression totals fold in HLO-baseline suppressions. Pinned by
    subprocess (the CLI is the contract surface)."""
    fixture = tmp_path / "mod.py"
    fixture.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n\n\n"
        "def f(x):\n"
        "    return jnp.float64(x)  # graftcheck: disable=GC001\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts", "run_checks.py"),
         str(fixture), "--no-contracts", "--format", "json", "--stats"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    stats = payload["stats"]
    assert stats["schema"] == 2
    assert stats["files"] == 1
    assert stats["findings_by_rule"] == {}
    assert stats["suppressions_by_rule"] == {"GC001": 1}
    assert stats["suppressions_total"] == 1
    # The GC20x rules are documented next to the AST/jaxpr ones.
    for rule in hlolint.HLO_RULES:
        assert rule in payload["rules"], rule


def test_bench_gate_hlo_rules(tmp_path):
    """The hlo rule class end to end through the CLI: a fresh payload
    at the committed floor passes; new findings / a fingerprint flip /
    lost coverage / fatter top-target bytes fail."""
    sys.path.insert(0, os.path.join(_ROOT, "scripts"))
    try:
        import bench_gate
    finally:
        sys.path.pop(0)
    part = {"programs": 19, "findings_total": 0,
            "findings_max_per_program": 0, "fingerprint_flips": 0,
            "top_target_bytes": 4.0e8}
    base = {"config_hlo": dict(part)}
    good = {"config_hlo": dict(part, top_target_bytes=4.2e8)}
    v = bench_gate.check_payload(base, good)
    hlo_rows = {c["name"]: c["status"] for c in v["checks"]
                if c["class"] == "hlo"}
    assert set(hlo_rows) == {
        "hlo_findings_total", "hlo_findings_per_program",
        "hlo_fingerprint_flips", "hlo_program_coverage",
        "hlo_top_target_bytes"}
    assert all(s == "pass" for s in hlo_rows.values()), hlo_rows
    bad = {"config_hlo": dict(part, findings_total=1,
                              findings_max_per_program=1,
                              fingerprint_flips=2, programs=18,
                              top_target_bytes=6.0e8)}
    v_bad = bench_gate.check_payload(base, bad)
    assert set(v_bad["failed"]) >= set(hlo_rows), v_bad["failed"]
    # Ledger trend coverage: the config_hlo paths ride BENCH_METRICS.
    from porqua_tpu.obs import ledger
    metrics = ledger.metrics_from_bench(good)
    assert metrics["config_hlo.top_target_bytes"] == 4.2e8
    assert metrics["config_hlo.findings_total"] == 0
