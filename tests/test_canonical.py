"""Canonical-form construction, padding and stacking."""

import numpy as np
import jax.numpy as jnp
import pytest

from porqua_tpu.qp.canonical import CanonicalQP, stack_qps


def _toy(n=3, m=2, n_max=None, m_max=None):
    P = np.eye(n)
    q = np.arange(1.0, n + 1)
    C = np.ones((m, n))
    l = np.zeros(m)
    u = np.ones(m)
    return CanonicalQP.build(P, q, C, l, u, lb=np.zeros(n), ub=np.ones(n),
                             n_max=n_max, m_max=m_max, dtype=jnp.float64)


def test_build_shapes():
    qp = _toy()
    assert qp.n == 3 and qp.m == 2
    assert qp.P.shape == (3, 3)
    assert qp.C.shape == (2, 3)


def test_padding():
    qp = _toy(n=3, m=2, n_max=5, m_max=4)
    assert qp.n == 5 and qp.m == 4
    # Padded vars: unit diag, pinned to 0
    assert float(qp.P[4, 4]) == 1.0
    assert float(qp.lb[4]) == 0.0 and float(qp.ub[4]) == 0.0
    assert float(qp.var_mask[3]) == 0.0
    # Padded rows: always-satisfied intervals
    assert np.isinf(float(qp.l[3])) and np.isinf(float(qp.u[3]))
    assert float(qp.row_mask[2]) == 0.0
    # Real data intact
    np.testing.assert_allclose(np.asarray(qp.P[:3, :3]), np.eye(3))


def test_padding_too_small_raises():
    with pytest.raises(ValueError):
        _toy(n=3, m=2, n_max=2)


def test_objective_value():
    qp = _toy()
    x = jnp.array([1.0, 0.0, 0.0])
    # 0.5 * 1 + q[0] * 1 = 1.5
    assert float(qp.objective_value(x)) == pytest.approx(1.5)


def test_stack():
    qps = [_toy(n_max=4, m_max=3) for _ in range(5)]
    batch = stack_qps(qps)
    assert batch.P.shape == (5, 4, 4)
    assert batch.l.shape == (5, 3)


def test_stack_shape_mismatch():
    with pytest.raises(ValueError):
        stack_qps([_toy(), _toy(n_max=5, m_max=4)])


def test_build_accepts_and_pads_objective_factor():
    rng = np.random.default_rng(0)
    T, n = 12, 5
    X = rng.standard_normal((T, n))
    P = 2 * X.T @ X + np.diag(np.full(n, 0.3))
    qp = CanonicalQP.build(
        P, np.zeros(n), C=np.ones((1, n)), l=np.ones(1), u=np.ones(1),
        n_max=8, m_max=3, dtype=jnp.float64,
        Pf=X, Pdiag=np.full(n, 0.3),
    )
    assert qp.Pf.shape == (T, 8)
    # Padded variables carry P = I via the diagonal completion, so the
    # factor identity holds on the PADDED problem too.
    recon = 2 * np.asarray(qp.Pf).T @ np.asarray(qp.Pf) + np.diag(
        np.asarray(qp.Pdiag))
    np.testing.assert_allclose(recon, np.asarray(qp.P), atol=1e-12)


def test_build_rejects_inconsistent_factor():
    rng = np.random.default_rng(1)
    n = 4
    X = rng.standard_normal((6, n))
    P = 2 * X.T @ X
    with pytest.raises(ValueError, match="do not reproduce"):
        CanonicalQP.build(P, np.zeros(n), Pf=X * 1.01)
    with pytest.raises(ValueError, match="Pdiag without Pf"):
        CanonicalQP.build(P, np.zeros(n), Pdiag=np.ones(n))
