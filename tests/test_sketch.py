"""Contracts for the count-sketch Gram embedding and the sketch-fed
solve path (``SolverParams(sketch_dim=...)``).

Two layers are pinned:

* the embedding itself (``qp/sketch.py`` + the ``sketch_rows``
  primitive now owned by ``qp/canonical.py``): seeded determinism,
  the measured ``gram_rel_err`` certificate, passthrough policy;
* the threaded path (``SolverParams.sketch_dim`` ->
  ``tracking_step`` -> ``build_tracking_qp``): sketch_dim=0 is a
  bit-exact passthrough (the trace-time branch emits the identical
  program), the in-program sketch is bit-identical to the standalone
  ``sketched_tracking_qp`` embedding (one ``_sketch_window`` helper,
  two callers), and the sketch-fed solve keeps tracking error within
  a band of the dense reference on all three backends — with TE
  always evaluated against the TRUE window, never the sketched one.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from porqua_tpu.qp.admm import Status
from porqua_tpu.qp.canonical import sketch_rows
from porqua_tpu.qp.sketch import (
    SketchParams,
    count_sketch,
    gram_rel_err,
    sketched_tracking_qp,
)
from porqua_tpu.qp.solve import SolverParams
from porqua_tpu.tracking import (
    build_tracking_qp,
    synthetic_universe_np,
    tracking_step_jit,
)

T, N, D = 64, 48, 32

PARAMS = SolverParams(max_iter=2000, eps_abs=1e-6, eps_rel=1e-6,
                      polish=False, check_interval=25)


def _window(seed=3):
    Xs, ys = synthetic_universe_np(seed, 1, T, N)
    return jnp.asarray(Xs[0]), jnp.asarray(ys[0])


def _universe(seed=3, b=4):
    Xs, ys = synthetic_universe_np(seed, b, T, N)
    return jnp.asarray(Xs), jnp.asarray(ys)


# ---------------------------------------------------------------------------
# the embedding primitive
# ---------------------------------------------------------------------------

def test_sketch_rows_is_seeded_and_deterministic():
    X, _ = _window()
    key = jax.random.key(11)
    a = np.asarray(sketch_rows(X, D, key))
    b = np.asarray(sketch_rows(X, D, key))
    np.testing.assert_array_equal(a, b)
    c = np.asarray(sketch_rows(X, D, jax.random.key(12)))
    assert np.any(a != c), "different seeds must give different sketches"
    # count_sketch is the same primitive (the qp.sketch alias).
    np.testing.assert_array_equal(np.asarray(count_sketch(X, D, key)), a)


def test_gram_rel_err_certificate_is_real():
    """The probe bound actually tracks embedding quality: it shrinks
    as the sketch widens and is exactly measurable (not assumed)."""
    X, _ = _window()
    key = jax.random.key(0)
    k_s, k_p = jax.random.split(key)
    errs = []
    for d in (8, 16, 48):
        Xs = sketch_rows(X, d, k_s)
        errs.append(float(gram_rel_err(X, Xs, k_p, probes=8)))
    assert errs[0] > errs[-1], errs
    assert all(e > 0.0 for e in errs)


# ---------------------------------------------------------------------------
# the threaded (sketch-fed) path
# ---------------------------------------------------------------------------

def test_sketch_dim_zero_is_bit_exact_passthrough():
    """sketch_dim=0 — and a non-compressing sketch_dim >= T — emit the
    identical assembly (trace-time branch): every QP field bit-equal."""
    X, y = _window()
    base = build_tracking_qp(X, y)
    for d in (0, T, T + 7):
        qp = build_tracking_qp(X, y, sketch_dim=d, sketch_seed=5)
        for name in ("P", "q", "C", "l", "u", "lb", "ub", "constant",
                     "Pf", "Pdiag"):
            np.testing.assert_array_equal(
                np.asarray(getattr(qp, name)),
                np.asarray(getattr(base, name)), err_msg=f"{name} d={d}")


def test_threaded_sketch_matches_sketched_tracking_qp():
    """The in-program embedding (build_tracking_qp(sketch_dim=d)) and
    the standalone certificate path (sketched_tracking_qp) derive the
    sketch from one shared helper — the assembled QPs are bit-equal."""
    X, y = _window()
    qp_a = build_tracking_qp(X, y, sketch_dim=D, sketch_seed=9)
    qp_b, info = sketched_tracking_qp(X, y, SketchParams(D, seed=9))
    assert int(info.sketch_dim) == D
    assert qp_a.Pf.shape[0] == D
    for name in ("P", "q", "constant", "Pf", "Pdiag"):
        np.testing.assert_array_equal(
            np.asarray(getattr(qp_a, name)),
            np.asarray(getattr(qp_b, name)), err_msg=name)


@pytest.mark.parametrize("method", ["admm", "pdhg", "napg"])
def test_sketch_fed_solve_te_band(method):
    """The full jitted path with ``params.sketch_dim`` set solves the
    embedded problem on every backend and lands within a TE band of
    the dense reference — TE evaluated on the true window for both."""
    Xs, ys = _universe()
    dense_p = dataclasses.replace(PARAMS, method=method)
    if method == "pdhg":
        # PDHG is the wrong backend for the box-only tracking family
        # (the regime NAPG exists for — see BENCH_r12 config_pdhg): it
        # needs a looser target to retire SOLVED in CI time. The pin
        # here is that the sketch-fed path works per backend, not that
        # every backend is competitive on this bucket.
        dense_p = dataclasses.replace(dense_p, eps_abs=1e-4,
                                      eps_rel=1e-4, max_iter=4000)
    sk_p = dataclasses.replace(dense_p, sketch_dim=D, sketch_seed=1)
    dense = tracking_step_jit(Xs, ys, dense_p)
    sk = tracking_step_jit(Xs, ys, sk_p)
    assert np.all(np.asarray(dense.status) == Status.SOLVED)
    assert np.all(np.asarray(sk.status) == Status.SOLVED)
    te_d = np.asarray(dense.tracking_error)
    te_s = np.asarray(sk.tracking_error)
    # The dense TE sits at the benchmark's noise floor, so the honest
    # relative band is coarse at CI sizes: a half-length sketch lands
    # within ~2x of the floor (the committed config_sketch artifact
    # shows 0.33 at production window/dim ratios; the bench gate holds
    # the north-star run to its measured band, not this smoke bar).
    drift = np.max((te_s - te_d) / np.maximum(te_d, 1e-12))
    assert drift < 2.0, (te_d, te_s)
    # Feasibility is unaffected by the sketch (same polytope).
    # First-order iterates satisfy it to their own eps target (NAPG's
    # prox is exact; ADMM/PDHG leave eps-scale slack).
    slack = 10.0 * dense_p.eps_abs
    w = np.asarray(sk.weights)
    np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=slack)
    assert float(w.min()) >= -slack


def test_wider_sketch_tracks_better():
    """Embedding quality is monotone-ish in the sketch width: a
    three-quarter-length sketch beats a quarter-length one on TE
    (the knob the north-star run turns to buy accuracy)."""
    Xs, ys = _universe()
    te = {}
    for d in (16, 48):
        p = dataclasses.replace(PARAMS, sketch_dim=d, sketch_seed=1)
        te[d] = float(np.mean(np.asarray(
            tracking_step_jit(Xs, ys, p).tracking_error)))
    assert te[48] < te[16], te


def test_sketch_fed_params_are_distinct_executables():
    """sketch_dim is static params state: distinct values are distinct
    jit keys (distinct Pf row-count programs), same as method — the
    serving cache treats them as different buckets by construction."""
    p0 = dataclasses.replace(PARAMS, sketch_dim=0)
    p1 = dataclasses.replace(PARAMS, sketch_dim=D)
    assert hash(p0) != hash(p1)
    assert p0 != p1
