"""Device-vectorized accounting vs the pandas golden reference.

``porqua_tpu.accounting.simulate`` must reproduce ``Strategy.simulate``
(the reference's return engine, ``src/portfolio.py:205-245``) on the
rescale=False path, including margin/cash/loan sleeves, turnover
variable costs and day-count fixed costs.
"""

import numpy as np
import pandas as pd
import pytest

from porqua_tpu.accounting import simulate_strategy
from porqua_tpu.portfolio import Portfolio, Strategy


def make_returns(rng, n_assets=5, n_days=200):
    dates = pd.bdate_range("2021-01-04", periods=n_days)
    return pd.DataFrame(
        rng.standard_normal((n_days, n_assets)) * 0.01,
        index=dates,
        columns=[f"A{i}" for i in range(n_assets)],
    )


def make_strategy(returns, weight_rows, every=40, start=10):
    dates = returns.index[start::every][: len(weight_rows)]
    strategy = Strategy([])
    for d, w in zip(dates, weight_rows):
        strategy.portfolios.append(
            Portfolio(str(d.date()), dict(zip(returns.columns, w)))
        )
    return strategy


def test_simulate_long_only_matches_pandas(rng):
    returns = make_returns(rng)
    w = [rng.dirichlet(np.ones(5)) for _ in range(4)]
    strategy = make_strategy(returns, w)

    ref = strategy.simulate(return_series=returns, fc=0, vc=0)
    fast = simulate_strategy(strategy, returns, fc=0, vc=0)

    common = ref.index.intersection(fast.index)
    assert len(common) > 100
    np.testing.assert_allclose(
        fast[common].to_numpy(), ref[common].to_numpy(), atol=1e-10
    )


def test_simulate_long_short_with_sleeves(rng):
    returns = make_returns(rng)
    w = []
    for _ in range(3):
        row = rng.standard_normal(5) * 0.4
        w.append(row)
    strategy = make_strategy(returns, w)

    ref = strategy.simulate(return_series=returns, fc=0, vc=0)
    fast = simulate_strategy(strategy, returns, fc=0, vc=0)
    common = ref.index.intersection(fast.index)
    np.testing.assert_allclose(
        fast[common].to_numpy(), ref[common].to_numpy(), atol=1e-10
    )


def test_simulate_fixed_costs(rng):
    returns = make_returns(rng)
    w = [rng.dirichlet(np.ones(5)) for _ in range(3)]
    strategy = make_strategy(returns, w)

    ref = strategy.simulate(return_series=returns, fc=0.01, vc=0)
    fast = simulate_strategy(strategy, returns, fc=0.01, vc=0)
    common = ref.index.intersection(fast.index)
    np.testing.assert_allclose(
        fast[common].to_numpy(), ref[common].to_numpy(), atol=1e-9
    )


def test_simulate_variable_costs_turnover(rng):
    returns = make_returns(rng)
    w = [rng.dirichlet(np.ones(5)) for _ in range(4)]
    strategy = make_strategy(returns, w)

    ref = strategy.simulate(return_series=returns, fc=0, vc=0.002)
    fast = simulate_strategy(strategy, returns, fc=0, vc=0.002)
    common = ref.index.intersection(fast.index)
    np.testing.assert_allclose(
        fast[common].to_numpy(), ref[common].to_numpy(), atol=1e-9
    )
