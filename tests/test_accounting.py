"""Device-vectorized accounting vs the pandas golden reference.

``porqua_tpu.accounting.simulate`` must reproduce ``Strategy.simulate``
(the reference's return engine, ``src/portfolio.py:205-245``) on the
rescale=False path, including margin/cash/loan sleeves, turnover
variable costs and day-count fixed costs.
"""

import numpy as np
import pandas as pd
import pytest

from porqua_tpu.accounting import simulate_strategy
from porqua_tpu.portfolio import Portfolio, Strategy


def make_returns(rng, n_assets=5, n_days=200):
    dates = pd.bdate_range("2021-01-04", periods=n_days)
    return pd.DataFrame(
        rng.standard_normal((n_days, n_assets)) * 0.01,
        index=dates,
        columns=[f"A{i}" for i in range(n_assets)],
    )


def make_strategy(returns, weight_rows, every=40, start=10):
    dates = returns.index[start::every][: len(weight_rows)]
    strategy = Strategy([])
    for d, w in zip(dates, weight_rows):
        strategy.portfolios.append(
            Portfolio(str(d.date()), dict(zip(returns.columns, w)))
        )
    return strategy


def test_simulate_long_only_matches_pandas(rng):
    returns = make_returns(rng)
    w = [rng.dirichlet(np.ones(5)) for _ in range(4)]
    strategy = make_strategy(returns, w)

    ref = strategy.simulate(return_series=returns, fc=0, vc=0)
    fast = simulate_strategy(strategy, returns, fc=0, vc=0)

    common = ref.index.intersection(fast.index)
    assert len(common) > 100
    np.testing.assert_allclose(
        fast[common].to_numpy(), ref[common].to_numpy(), atol=1e-10
    )


def test_simulate_long_short_with_sleeves(rng):
    returns = make_returns(rng)
    w = []
    for _ in range(3):
        row = rng.standard_normal(5) * 0.4
        w.append(row)
    strategy = make_strategy(returns, w)

    ref = strategy.simulate(return_series=returns, fc=0, vc=0)
    fast = simulate_strategy(strategy, returns, fc=0, vc=0)
    common = ref.index.intersection(fast.index)
    np.testing.assert_allclose(
        fast[common].to_numpy(), ref[common].to_numpy(), atol=1e-10
    )


def test_simulate_fixed_costs(rng):
    returns = make_returns(rng)
    w = [rng.dirichlet(np.ones(5)) for _ in range(3)]
    strategy = make_strategy(returns, w)

    ref = strategy.simulate(return_series=returns, fc=0.01, vc=0)
    fast = simulate_strategy(strategy, returns, fc=0.01, vc=0)
    common = ref.index.intersection(fast.index)
    np.testing.assert_allclose(
        fast[common].to_numpy(), ref[common].to_numpy(), atol=1e-9
    )


def test_simulate_variable_costs_turnover(rng):
    returns = make_returns(rng)
    w = [rng.dirichlet(np.ones(5)) for _ in range(4)]
    strategy = make_strategy(returns, w)

    ref = strategy.simulate(return_series=returns, fc=0, vc=0.002)
    fast = simulate_strategy(strategy, returns, fc=0, vc=0.002)
    common = ref.index.intersection(fast.index)
    np.testing.assert_allclose(
        fast[common].to_numpy(), ref[common].to_numpy(), atol=1e-9
    )


def test_simulate_rejects_non_trading_rebalance_dates(rng):
    """Variable costs are charged on the rebalance date's own return
    row (the reference's convention); a rebalance date outside the
    return-series index must produce a diagnosis naming the dates, not
    a pandas KeyError from deep inside ``.loc``."""
    returns = make_returns(rng)
    strategy = Strategy([])
    # Second date is a Saturday — not in the bdate_range index.
    for d, w in zip(["2021-01-14", "2021-01-16", "2021-03-04"],
                    [rng.dirichlet(np.ones(5)) for _ in range(3)]):
        strategy.portfolios.append(
            Portfolio(d, dict(zip(returns.columns, w))))
    with pytest.raises(ValueError, match="2021-01-16"):
        strategy.simulate(return_series=returns, fc=0, vc=0.002)


def test_turnover_rescale_true_long_short(rng):
    """VERDICT item 7: the rescale=True drift (long/short renormalized,
    reference portfolio.py:283-286) must have a device equivalent —
    device turnover with rescale matches Strategy.turnover(rescale=True)
    on a long-short strategy, and the two modes genuinely differ."""
    import jax.numpy as jnp

    from porqua_tpu.accounting import simulate

    returns = make_returns(rng)
    w = [np.array([0.8, 0.6, -0.3, -0.1, 0.0]),
         np.array([0.5, 0.4, -0.2, 0.3, 0.0]),
         np.array([0.3, 0.3, 0.4, -0.5, 0.5])]
    strategy = make_strategy(returns, w)

    ref_true = strategy.turnover(return_series=returns, rescale=True)
    ref_false = strategy.turnover(return_series=returns, rescale=False)
    assert not np.allclose(ref_true.values[1:], ref_false.values[1:])

    W = strategy.get_weights_df().reindex(
        columns=returns.columns).fillna(0.0).to_numpy()
    reb_idx = returns.index.get_indexer(
        pd.to_datetime(strategy.get_rebalancing_dates()), method="pad")
    for rescale, ref in ((True, ref_true), (False, ref_false)):
        out = simulate(jnp.asarray(W), jnp.asarray(returns.to_numpy()),
                       jnp.asarray(reb_idx), rescale_turnover=rescale)
        np.testing.assert_allclose(
            np.asarray(out.turnover), ref.values, rtol=1e-8, atol=1e-10)


def test_drift_weights_matches_floating_weights(rng):
    """Device drift (one global cumprod + searchsorted) must match the
    pandas floating_weights path row-for-row, in both rescale modes,
    including short positions."""
    import jax.numpy as jnp

    from porqua_tpu.accounting import drift_weights
    from porqua_tpu.portfolio import floating_weights

    returns = make_returns(rng)
    w0 = {"A0": 0.9, "A1": 0.5, "A2": -0.4, "A3": 0.0, "A4": 0.0}
    start, end = returns.index[10], returns.index[60]

    for rescale in (False, True):
        ref = floating_weights(returns, w0, start, end, rescale=rescale)
        dev = drift_weights(
            jnp.asarray(list(w0.values()), jnp.float64)[None, :],
            jnp.asarray(returns.to_numpy()),
            jnp.asarray([10]), rescale=rescale)
        np.testing.assert_allclose(
            np.asarray(dev)[10:61], ref.to_numpy(), rtol=1e-9, atol=1e-12)


def test_performance_summary_metrics(rng):
    """Sharpe/vol/drawdown/VaR against hand-computed values on a known
    series; benchmark block adds TE/beta/active return (the
    quantstats-style set the reference notebooks print)."""
    from porqua_tpu.accounting import performance_summary

    r = pd.Series(
        rng.standard_normal(500) * 0.01 + 0.0004,
        index=pd.bdate_range("2020-01-01", periods=500))
    bench = 0.8 * r + pd.Series(
        rng.standard_normal(500) * 0.004,
        index=r.index)
    perf = performance_summary(r, benchmark=bench)

    assert perf["n_days"] == 500
    np.testing.assert_allclose(
        perf["sharpe"], r.mean() / r.std() * np.sqrt(252), rtol=1e-12)
    levels = (1 + r).cumprod()
    np.testing.assert_allclose(
        perf["max_drawdown"], (levels / levels.cummax() - 1).min(),
        rtol=1e-12)
    np.testing.assert_allclose(perf["var_95"], r.quantile(0.05), rtol=1e-12)
    np.testing.assert_allclose(
        perf["cumulative_return"], levels.iloc[-1] - 1, rtol=1e-12)
    # annual_return is CAGR from the level path (quantstats
    # convention), so it must be consistent with cumulative_return:
    # (1 + annual) ** (n/252) == 1 + cumulative.
    np.testing.assert_allclose(
        (1 + perf["annual_return"]) ** (500 / 252),
        levels.iloc[-1], rtol=1e-10)
    np.testing.assert_allclose(
        perf["tracking_error"], (r - bench).std() * np.sqrt(252),
        rtol=1e-12)
    np.testing.assert_allclose(
        perf["beta"], r.cov(bench) / bench.var(), rtol=1e-12)


def test_performance_summary_degenerate_series():
    """Empty and flat series report NaN metrics, never crash or +inf."""
    from porqua_tpu.accounting import performance_summary

    empty = performance_summary(pd.Series([], dtype=float),
                                benchmark=pd.Series([], dtype=float))
    assert empty["n_days"] == 0 and np.isnan(empty["sharpe"])
    assert np.isnan(empty["beta"])

    flat = performance_summary(
        pd.Series(-0.001, index=pd.bdate_range("2022-01-03", periods=50)))
    assert np.isnan(flat["sharpe"])  # no variance -> undefined, not +inf
    assert flat["cumulative_return"] < 0
