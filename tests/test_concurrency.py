"""graftcheck concurrency plane: GC008-GC010 static rules +
the ``PORQUA_TSAN=1`` runtime lock-order sanitizer.

Mirrors tests/test_analysis.py's structure: one seeded violation per
rule asserting rule id + line number, the clean-shape controls, and
the shipped-tree self-scan (which lives in test_analysis.py's
``test_self_scan_shipped_tree_is_clean`` — GC008-GC010 are part of the
default rule set, so that gate covers them too). The two-lock
order-inversion repro is ONE source fixture caught both statically
(GC009, with both acquisition sites in the message) and at runtime
(executing it under ``PORQUA_TSAN=1`` raises ``SanitizerError``).
"""

import textwrap
import threading
import time

import numpy as np
import pytest

import porqua_tpu
from porqua_tpu.analysis import sanitize, tsan
from porqua_tpu.analysis.lint import scan_paths


def write_fixture(tmp_path, relpath, source):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return str(path)


def findings_for(tmp_path, relpath, source, rules=None):
    path = write_fixture(tmp_path, relpath, source)
    return [(f.rule, f.line) for f in scan_paths([path], rules=rules)]


# ---------------------------------------------------------------------------
# GC008 — shared-state inference
# ---------------------------------------------------------------------------

GC008_SRC = """\
    import threading


    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._done = []  # guarded-by: self._lock
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._run, name="w")
            self._t.start()

        def _run(self):
            self._n += 1
            with self._lock:
                self._done.append(1)

        def bump(self):
            self._n += 1

        def safe_bump(self):
            with self._lock:
                self._n += 1
    """


def test_gc008_multi_root_mutation_detected(tmp_path):
    hits = findings_for(tmp_path, "serve/mod.py", GC008_SRC,
                        rules={"GC008"})
    # _n is written by the spawned thread (_run, line 16) AND by the
    # caller-thread API (bump, line 21) with no lock; the locked write
    # in safe_bump is NOT flagged; the annotated _done is GC006's.
    assert hits == [("GC008", 16), ("GC008", 21)]


def test_gc008_single_root_and_locked_state_clean(tmp_path):
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import threading


        class OneRoot:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = {}
                self._stopping = threading.Event()

            def start(self):
                self._t = threading.Thread(target=self._run)
                self._stopping.clear()
                self._t.start()

            def _run(self):
                # dispatch-thread-only state: one root, no lock needed
                self._pending["x"] = 1
                self._pending.clear()


        class AllLocked:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._n += 1

            def bump(self):
                with self._lock:
                    self._n += 1
        """, rules={"GC008"})
    assert hits == []


def test_gc008_caller_holds_annotation_protects(tmp_path):
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import threading


        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._trip()

            def _trip(self):  # guarded-by: self._lock
                self._n += 1

            def bump(self):
                with self._lock:
                    self._n += 1
        """, rules={"GC008"})
    assert hits == []


def test_gc008_callback_root_counts(tmp_path):
    hits = findings_for(tmp_path, "serve/mod.py", """\
        class R:
            def __init__(self, svc):
                self.svc = svc
                self._hits = 0

            def submit(self):
                t = self.svc.submit()
                t.add_done_callback(lambda f: self._note())
                self._hits += 1

            def _note(self):
                self._hits += 1
        """, rules={"GC008"})
    # api root (submit, line 9) + the escaped-callback root (_note,
    # line 12) both write _hits unlocked.
    assert hits == [("GC008", 9), ("GC008", 12)]


# ---------------------------------------------------------------------------
# GC009 — static deadlock detection (+ the shared runtime repro below)
# ---------------------------------------------------------------------------

#: The two-lock inversion fixture: scanned statically AND executed
#: under PORQUA_TSAN=1 — the same discipline, both halves.
INVERSION_SRC = """\
    from porqua_tpu.analysis import tsan


    class AB:
        def __init__(self):
            self._a = tsan.lock("fxA")
            self._b = tsan.lock("fxB")

        def fwd(self):
            with self._a:
                with self._b:
                    pass

        def rev(self):
            with self._b:
                with self._a:
                    pass
    """


def test_gc009_inversion_reports_both_sites(tmp_path):
    path = write_fixture(tmp_path, "serve/inv.py", INVERSION_SRC)
    findings = scan_paths([path], rules={"GC009"})
    assert [(f.rule, f.line) for f in findings] == [("GC009", 10)]
    msg = findings[0].message
    # both acquisition sites named: fwd's inner (line 11), rev's
    # outer/inner (lines 15/16)
    assert "inv.py:11" in msg
    assert "inv.py:15" in msg and "inv.py:16" in msg


def test_runtime_tsan_catches_the_same_inversion(tmp_path, monkeypatch):
    monkeypatch.setenv("PORQUA_TSAN", "1")
    tsan.reset()
    try:
        ns: dict = {}
        exec(compile(textwrap.dedent(INVERSION_SRC), "inv.py", "exec"), ns)
        ab = ns["AB"]()
        ab.fwd()
        with pytest.raises(sanitize.SanitizerError,
                           match="lock-order inversion"):
            ab.rev()
        assert any("fxA" in v and "fxB" in v for v in tsan.violations())
    finally:
        tsan.reset()


def test_gc009_cross_object_cycle_through_call_graph(tmp_path):
    hits = findings_for(tmp_path, "serve/xobj.py", """\
        import threading


        class Inner:
            def __init__(self, owner: "Outer"):
                self._lock = threading.Lock()
                self.owner = owner

            def poke(self):
                with self._lock:
                    self.owner.note()


        class Outer:
            def __init__(self):
                self._lock = threading.Lock()
                self.inner = Inner(self)

            def go(self):
                with self._lock:
                    self.inner.poke()

            def note(self):
                with self._lock:
                    pass
        """, rules={"GC009"})
    assert [h[0] for h in hits] == ["GC009"]


def test_gc009_consistent_order_clean(tmp_path):
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import threading


        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """, rules={"GC009"})
    assert hits == []


# ---------------------------------------------------------------------------
# GC010 — blocking call under a lock
# ---------------------------------------------------------------------------

def test_gc010_untimed_queue_and_sleep_under_lock(tmp_path):
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import queue
        import threading
        import time


        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.q = queue.Queue()

            def drain(self):
                with self._lock:
                    item = self.q.get()
                    time.sleep(0.1)
                return item

            def ok(self):
                with self._lock:
                    self.q.put(1, timeout=1.0)
                    return self.q.get(timeout=1.0)

            def also_ok(self):
                item = self.q.get()
                time.sleep(0.1)
                return item
        """, rules={"GC010"})
    assert hits == [("GC010", 13), ("GC010", 14)]


def test_gc010_result_compile_and_transitive(tmp_path):
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import threading


        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def wait_under_lock(self, fut):
                with self._lock:
                    return fut.result()

            def compile_under_lock(self, jit_fn, x):
                with self._lock:
                    return jit_fn(x).lower(x).compile()

            def indirect(self, fut):
                with self._lock:
                    return self._helper(fut)

            def _helper(self, fut):
                return fut.result()

            def bounded(self, fut):
                with self._lock:
                    return fut.result(timeout=5.0)
        """, rules={"GC010"})
    assert ("GC010", 10) in hits   # untimed result()
    assert ("GC010", 14) in hits   # jit(...).lower(...).compile()
    assert ("GC010", 21) in hits   # reached through the call graph
    assert not any(line == 26 for _, line in hits)  # timeout'd: clean


def test_gc010_untimed_event_wait_flagged_condition_wait_exempt(tmp_path):
    # An untimed Event.wait() under a lock is the unbounded-wait
    # deadlock class itself (the setter may need the lock we hold);
    # Condition.wait RELEASES its lock while blocked and stays exempt,
    # as does any timeout-bounded wait.
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import threading


        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._done = threading.Event()

            def bad(self):
                with self._lock:
                    self._done.wait()

            def ok_timeout(self):
                with self._lock:
                    self._done.wait(1.0)
                    self._done.wait(timeout=1.0)

            def ok_condition(self):
                with self._cond:
                    self._cond.wait()
        """, rules={"GC010"})
    assert hits == [("GC010", 12)]


def test_gc010_block_true_is_not_a_bound(tmp_path):
    # block=True leaves the put unbounded (it is the default!);
    # block=False makes it non-blocking. Only the latter exempts.
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import queue
        import threading


        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.q = queue.Queue()

            def bad(self, item):
                with self._lock:
                    self.q.put(item, block=True)

            def ok(self, item):
                with self._lock:
                    self.q.put(item, block=False)
        """, rules={"GC010"})
    assert hits == [("GC010", 12)]


def test_gc008_positional_thread_target_and_timer_function_kwarg(tmp_path):
    # Thread(group, target, ...) — the FIRST positional slot is group;
    # Timer's callback may arrive as the `function=` keyword. Both
    # spellings must root, or races on those paths scan clean.
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import threading


        class C:
            def __init__(self):
                self._n = 0
                self._m = 0

            def start(self):
                threading.Thread(None, self._loop).start()
                threading.Timer(5.0, function=self._flush).start()

            def _loop(self):
                self._n += 1

            def _flush(self):
                self._m += 1

            def bump(self):
                self._n += 1
                self._m += 1
        """, rules={"GC008"})
    assert hits == [("GC008", 14), ("GC008", 17),
                    ("GC008", 20), ("GC008", 21)]


def test_gc008_tuple_assign_reports_both_attrs(tmp_path):
    # `self._a, self._b = f()` mutates two attributes on ONE line;
    # dedup must not drop the second.
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import threading


        class C:
            def __init__(self):
                self._a = 0
                self._b = 0

            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                self._a, self._b = 1, 2

            def reset(self):
                self._a, self._b = 0, 0
        """, rules={"GC008"})
    assert sorted(hits) == [("GC008", 13), ("GC008", 13),
                            ("GC008", 16), ("GC008", 16)]


def test_gc010_positional_block_and_timeout_spellings(tmp_path):
    # get(False) is non-blocking, get(True, 1.0) is timeout-bounded —
    # both positional spellings exempt; put(item, True) stays flagged.
    hits = findings_for(tmp_path, "serve/mod.py", """\
        import queue
        import threading


        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.q = queue.Queue()

            def bad(self, item):
                with self._lock:
                    self.q.put(item, True)

            def ok(self):
                with self._lock:
                    a = self.q.get(False)
                    b = self.q.get(True, 1.0)
                    return a, b
        """, rules={"GC010"})
    assert hits == [("GC010", 12)]


def test_gc008_bound_method_callback_is_a_root(tmp_path):
    # A bound method escaping as a callback
    # (fut.add_done_callback(self._on_done)) runs on whatever thread
    # the holder chooses — same rooting as a lambda; a **kwargs spread
    # of a data attribute through a property is NOT an escape.
    hits = findings_for(tmp_path, "serve/mod.py", """\
        class C:
            def __init__(self):
                self._hits = 0
                self._kw = {}

            def submit(self, fut):
                fut.add_done_callback(self._on_done)

            def call(self, fn):
                fn(**self._kw)

            def _on_done(self, fut):
                self._hits += 1

            def bump(self):
                self._hits += 1
        """, rules={"GC008"})
    assert hits == [("GC008", 13), ("GC008", 16)]


# ---------------------------------------------------------------------------
# the shipped tree: concurrency plane scans clean, zero suppressions
# ---------------------------------------------------------------------------

def test_concurrency_rules_clean_on_shipped_tree():
    import os

    pkg = os.path.dirname(os.path.abspath(porqua_tpu.__file__))
    stats: dict = {}
    findings = scan_paths([pkg], rules={"GC008", "GC009", "GC010"},
                          stats_out=stats)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert stats["suppressions_by_rule"] == {}


def test_stats_count_findings_and_suppressions(tmp_path):
    write_fixture(tmp_path, "serve/mod.py", GC008_SRC)
    write_fixture(tmp_path, "serve/sup.py", """\
        import queue
        import threading
        import time


        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    time.sleep(1)  # graftcheck: disable=GC010
        """)
    stats: dict = {}
    findings = scan_paths([str(tmp_path)],
                          rules={"GC008", "GC009", "GC010"},
                          stats_out=stats)
    assert stats["findings_by_rule"] == {"GC008": 2}
    assert stats["suppressions_by_rule"] == {"GC010": 1}
    assert stats["files"] == 2
    assert len(findings) == 2


# ---------------------------------------------------------------------------
# runtime sanitizer: budgets, watchdog, serve e2e
# ---------------------------------------------------------------------------

def test_tsan_disabled_returns_plain_lock(monkeypatch):
    monkeypatch.delenv("PORQUA_TSAN", raising=False)
    lk = tsan.lock("plain")
    assert not isinstance(lk, tsan.TSanLock)
    with lk:
        pass


def test_tsan_reacquisition_raises(monkeypatch):
    monkeypatch.setenv("PORQUA_TSAN", "1")
    tsan.reset()
    try:
        a = tsan.lock("reacq")
        with pytest.raises(tsan.DeadlockError, match="re-acquisition"):
            with a:
                with a:
                    pass
        assert not a.locked()  # the raise released the outer hold
    finally:
        tsan.reset()


def test_tsan_hold_budget(monkeypatch):
    monkeypatch.setenv("PORQUA_TSAN", "1")
    monkeypatch.setenv("PORQUA_TSAN_HOLD_BUDGET_S", "0.02")
    tsan.reset()
    try:
        c = tsan.lock("holder")
        with pytest.raises(tsan.LockHoldError, match="held"):
            with c:
                time.sleep(0.06)
        # raised AFTER release: other threads are not wedged
        assert not c.locked()
    finally:
        tsan.reset()


def test_tsan_watchdog_max_wait(monkeypatch):
    monkeypatch.setenv("PORQUA_TSAN", "1")
    monkeypatch.setenv("PORQUA_TSAN_MAX_WAIT_S", "0.15")
    tsan.reset()
    try:
        d = tsan.lock("contended")

        def holder():
            d.acquire()
            time.sleep(0.8)
            d.release()

        t = threading.Thread(target=holder)
        t.start()
        time.sleep(0.05)
        with pytest.raises(tsan.DeadlockError, match="MAX_WAIT"):
            d.acquire()
        t.join()
    finally:
        tsan.reset()


def test_tsan_waitfor_cycle_detection(monkeypatch):
    """The watchdog's wait-for walk, driven directly: thread T holds A
    and (per the registered state) waits for B, whose owner is us —
    our acquire of A must report the closed cycle rather than block
    forever. (In normal operation the order-graph check preempts this;
    the watchdog is the backstop for orderings the graph has not
    seen — e.g. after a reset, or locks acquired via uninstrumented
    paths.)"""
    monkeypatch.setenv("PORQUA_TSAN", "1")
    tsan.reset()
    try:
        a, b = tsan.lock("wfA"), tsan.lock("wfB")
        me = threading.get_ident()
        other = me + 1  # a synthetic peer thread ident
        a._inner.acquire()  # "other" holds A...
        with tsan._graph_lock:
            tsan._owners[id(a)] = other
            tsan._waiting[other] = b   # ...and waits for B...
            tsan._owners[id(b)] = me   # ...which we own.
        with pytest.raises(tsan.DeadlockError, match="deadlock"):
            a._acquire_watched(me)
    finally:
        tsan.reset()


def test_tsan_hold_breach_does_not_mask_inflight_exception(monkeypatch):
    """A hold-budget breach during exception unwind must not REPLACE
    the real error: the caller diagnoses the original failure, the
    violation stays recorded for violations()."""
    monkeypatch.setenv("PORQUA_TSAN", "1")
    monkeypatch.setenv("PORQUA_TSAN_HOLD_BUDGET_S", "0.02")
    tsan.reset()
    try:
        lk = tsan.lock("unwind")
        with pytest.raises(ValueError, match="the real failure"):
            with lk:
                time.sleep(0.06)
                raise ValueError("the real failure")
        assert not lk.locked()
        assert any("held" in v for v in tsan.violations())
    finally:
        tsan.reset()


def test_tsan_foreign_release_refused(monkeypatch):
    """threading.Lock is not owner-checked: a thread releasing a lock
    it does not hold would slip through, corrupt the owner table the
    watchdog walks, and blame the real owner later. The sanitizer
    refuses it up front, leaving the hold intact."""
    monkeypatch.setenv("PORQUA_TSAN", "1")
    tsan.reset()
    try:
        lk = tsan.lock("foreign")
        lk.acquire()
        err = []

        def thief():
            try:
                lk.release()
            except sanitize.SanitizerError as e:
                err.append(e)

        t = threading.Thread(target=thief)
        t.start()
        t.join()
        assert err and "does not hold" in str(err[0])
        assert lk.locked()          # the foreign release released nothing
        lk.release()                # the owner's release still works
        assert not lk.locked()
    finally:
        tsan.reset()


def test_tsan_hold_breach_inside_condition_wait(monkeypatch):
    """A hold-budget breach whose release happens inside
    Condition.wait's _release_save must be RECORDED but not raised:
    raising into threading's wait protocol aborts wait() with the lock
    not re-acquired, and the enclosing `with cond:` exit then masks
    the diagnostic with "release unlocked lock"."""
    monkeypatch.setenv("PORQUA_TSAN", "1")
    monkeypatch.setenv("PORQUA_TSAN_HOLD_BUDGET_S", "0.02")
    tsan.reset()
    try:
        lk = tsan.lock("condheld")
        cond = threading.Condition(lk)

        def notifier():
            time.sleep(0.1)
            with cond:
                cond.notify()

        t = threading.Thread(target=notifier)
        # Hold past the budget, then wait: the breach fires on
        # _release_save's release. The Condition must stay coherent
        # (wait returns after notify; the exit release is clean).
        with cond:
            time.sleep(0.06)
            t.start()
            cond.wait(timeout=5.0)
        t.join()
        assert any("held" in v for v in tsan.violations())
    finally:
        tsan.reset()


SERVE_PARAMS = porqua_tpu.SolverParams(
    max_iter=300, eps_abs=1e-4, eps_rel=1e-4, polish=False,
    check_interval=25)


def make_qp(n=6, m=2, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * n, n))
    P = A.T @ A / (2 * n) + np.eye(n)
    q = rng.standard_normal(n)
    C = np.concatenate([np.ones((1, n)), rng.standard_normal((m - 1, n))])
    return porqua_tpu.CanonicalQP.build(
        P, q, C=C, l=np.full(m, -1.0), u=np.ones(m),
        lb=np.zeros(n), ub=np.ones(n))


def test_tsan_end_to_end_serve(monkeypatch):
    """PORQUA_TSAN=1 over a live service: the instrumented locks carry
    real traffic (caller threads + dispatch loop + warm-start cache),
    a forced breaker trip nests the health lock over the metrics and
    event locks (real order-graph edges), and the run completes with
    zero sanitizer violations."""
    monkeypatch.setenv("PORQUA_TSAN", "1")
    tsan.reset()
    try:
        from porqua_tpu.obs import Observability
        from porqua_tpu.serve import BucketLadder, SolveService
        from porqua_tpu.serve.metrics import ServeMetrics
        from porqua_tpu.serve.service import DeviceHealth

        obs = Observability()
        import jax

        cpu = jax.devices("cpu")[0]
        metrics = ServeMetrics()
        health = DeviceHealth(
            primary=cpu, fallback=cpu,
            probe_fn=lambda d: True,
            failure_threshold=1, probe_timeout_s=2.0,
            metrics=metrics, events=obs.events)
        svc = SolveService(params=SERVE_PARAMS,
                           ladder=BucketLadder(n_rungs=(8,), m_rungs=(4,)),
                           max_batch=4, max_wait_ms=1.0,
                           metrics=metrics, health=health, obs=obs)
        assert isinstance(svc.metrics._lock, tsan.TSanLock)
        assert isinstance(svc.cache._lock, tsan.TSanLock)
        assert isinstance(health._lock, tsan.TSanLock)
        with svc:
            svc.prewarm(make_qp())
            tickets = [svc.submit(make_qp(seed=i), warm_key=str(i % 3))
                       for i in range(24)]
            results = [svc.result(t, timeout=120) for t in tickets]
            assert all(r.found for r in results)
            # Force a breaker trip: record_failure -> _trip runs with
            # the health lock held and emits metrics + events — the
            # nested acquisitions the order graph exists to watch.
            health.record_failure(RuntimeError("induced"))
            assert svc.solve(make_qp(seed=99), timeout=120).found
        graph = tsan.order_graph()
        # _trip ran with the health lock held and reported through the
        # metrics + event sinks: real nested acquisitions, recorded.
        assert "DeviceHealth" in graph
        assert {"ServeMetrics", "EventBus"} <= graph["DeviceHealth"]
        assert tsan.violations() == []
    finally:
        tsan.reset()
