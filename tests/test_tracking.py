"""Flagship tracking program + driver entry points."""

import numpy as np

import jax
import jax.numpy as jnp

from porqua_tpu.qp import SolverParams, Status
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.solve import solve_qp
from porqua_tpu.tracking import (
    build_tracking_qp,
    synthetic_universe,
    tracking_step_jit,
)


def test_tracking_step_solves_and_tracks():
    Xs, ys = synthetic_universe(
        jax.random.PRNGKey(0), n_dates=6, window=80, n_assets=20,
        dtype=jnp.float64,
    )
    out = tracking_step_jit(Xs, ys, SolverParams(eps_abs=1e-8, eps_rel=1e-8))
    assert np.all(np.asarray(out.status) == Status.SOLVED)
    # Budget + box hold.
    sums = np.asarray(out.weights).sum(axis=1)
    np.testing.assert_allclose(sums, 1.0, atol=1e-6)
    assert np.asarray(out.weights).min() >= -1e-7
    # The benchmark is a noisy portfolio of the universe: tracking error
    # must land near the noise floor (1e-3), far below benchmark vol.
    assert float(np.median(np.asarray(out.tracking_error))) < 3e-3


def test_build_tracking_qp_matches_host_build():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((50, 12)) * 0.01
    w = rng.dirichlet(np.ones(12))
    y = X @ w
    dev = build_tracking_qp(jnp.asarray(X), jnp.asarray(y))
    host = CanonicalQP.build(
        2 * X.T @ X, -2 * X.T @ y,
        C=np.ones((1, 12)), l=np.ones(1), u=np.ones(1),
        lb=np.zeros(12), ub=np.ones(12),
        constant=float(y @ y), dtype=dev.P.dtype,
    )
    params = SolverParams(eps_abs=1e-8, eps_rel=1e-8)
    sd = solve_qp(dev, params)
    sh = solve_qp(host, params)
    np.testing.assert_allclose(np.asarray(sd.x), np.asarray(sh.x), atol=1e-7)


def test_graft_entry_compiles():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert np.all(np.isfinite(np.asarray(out.weights)))


def test_graft_dryrun_multichip():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
