"""Batched device backtest vs the serial engine, and the turnover scan.

The acceptance bar is exact agreement (to solver tolerance) between the
serial compat loop (reference semantics, ``Backtest.run``) and the
one-XLA-program batched path (``porqua_tpu.batch``).
"""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from porqua_tpu import (
    Backtest,
    BacktestService,
    LeastSquares,
    MeanVariance,
    OptimizationItemBuilder,
    SelectionItemBuilder,
)
from porqua_tpu.batch import (
    FIXED_UNIVERSE,
    build_problems,
    run_batch,
    solve_scan_turnover,
)
from porqua_tpu.builders import (
    bibfn_bm_series,
    bibfn_box_constraints,
    bibfn_budget_constraint,
    bibfn_return_series,
    bibfn_selection_data,
)
from porqua_tpu.constraints import Constraints
from porqua_tpu.qp import SolverParams, Status, stack_qps
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.lift import _as_parts, lift_turnover_constraint
from porqua_tpu.qp.solve import solve_qp


TIGHT = SolverParams(eps_abs=1e-8, eps_rel=1e-8, max_iter=20000)


def make_market(rng, n_assets=8, n_days=400):
    dates = pd.bdate_range("2020-01-01", periods=n_days)
    X = pd.DataFrame(
        rng.standard_normal((n_days, n_assets)) * 0.01,
        index=dates,
        columns=[f"A{i}" for i in range(n_assets)],
    )
    w_true = rng.dirichlet(np.ones(n_assets))
    y = pd.DataFrame(
        {"bm": X.to_numpy() @ w_true + rng.standard_normal(n_days) * 0.001},
        index=dates,
    )
    return {"return_series": X, "bm_series": y}


def make_service(data, rebdates, optimization, width=120):
    return BacktestService(
        data=data,
        selection_item_builders={
            "data": SelectionItemBuilder(bibfn=bibfn_selection_data),
        },
        optimization_item_builders={
            "returns": OptimizationItemBuilder(bibfn=bibfn_return_series, width=width),
            "bm": OptimizationItemBuilder(bibfn=bibfn_bm_series, width=width, align=True),
            "budget": OptimizationItemBuilder(bibfn=bibfn_budget_constraint),
            "box": OptimizationItemBuilder(bibfn=bibfn_box_constraints),
        },
        optimization=optimization,
        settings={"rebdates": rebdates, "quiet": True},
    )


@pytest.fixture
def market(rng):
    return make_market(rng)


def rebdates_of(data, k=6, every=30):
    idx = data["return_series"].index
    return [str(d.date()) for d in idx[150::every][:k]]


def test_batch_matches_serial_least_squares(market):
    rebdates = rebdates_of(market)

    serial_bs = make_service(market, rebdates, LeastSquares(dtype=jnp.float64, **TIGHT.__dict__))
    serial = Backtest()
    serial.run(serial_bs)

    batch_bs = make_service(market, rebdates, LeastSquares(dtype=jnp.float64, **TIGHT.__dict__))
    batched = run_batch(batch_bs, params=TIGHT, dtype=jnp.float64)

    assert np.all(batched.output["batch"]["status"] == Status.SOLVED)
    for date in rebdates:
        ws = pd.Series(serial.strategy.get_weights(date))
        wb = pd.Series(batched.strategy.get_weights(date))
        np.testing.assert_allclose(wb[ws.index], ws, atol=5e-6)


def test_batch_matches_serial_lad(market):
    """LAD through run_batch: the batched engine must resolve the same
    prox-form lowering AND the same solver-params overlay (round 5:
    halpern + fixed LP step) as the serial engine — params are derived
    via solver_params() after the problems are built, so both engines
    run the identical algorithm; weights then agree to vmap-level
    numerics."""
    from porqua_tpu import LAD

    rebdates = rebdates_of(market, k=4)

    serial_bs = make_service(market, rebdates, LAD(dtype=jnp.float64))
    serial = Backtest()
    serial.run(serial_bs)

    batch_bs = make_service(market, rebdates, LAD(dtype=jnp.float64))
    assert batch_bs.optimization.solver_params().halpern  # overlay active
    batched = run_batch(batch_bs, dtype=jnp.float64)

    for date in rebdates:
        ws = pd.Series(serial.strategy.get_weights(date))
        wb = pd.Series(batched.strategy.get_weights(date))
        assert abs(ws.sum() - 1.0) < 1e-6
        np.testing.assert_allclose(wb[ws.index], ws, atol=5e-4,
                                   err_msg=date)


def test_batch_matches_serial_mean_variance(market):
    rebdates = rebdates_of(market, k=4)

    serial_bs = make_service(market, rebdates, MeanVariance(dtype=jnp.float64, **TIGHT.__dict__))
    serial = Backtest()
    serial.run(serial_bs)

    batch_bs = make_service(market, rebdates, MeanVariance(dtype=jnp.float64, **TIGHT.__dict__))
    batched = run_batch(batch_bs, params=TIGHT, dtype=jnp.float64)

    for date in rebdates:
        ws = pd.Series(serial.strategy.get_weights(date))
        wb = pd.Series(batched.strategy.get_weights(date))
        np.testing.assert_allclose(wb[ws.index], ws, atol=5e-6)


def test_build_problems_pads_to_common_shape(market):
    rebdates = rebdates_of(market, k=5)
    bs = make_service(market, rebdates, LeastSquares(dtype=jnp.float64, **TIGHT.__dict__))
    problems = build_problems(bs, dtype=jnp.float64)
    assert problems.qp.P.shape[0] == len(rebdates)
    assert problems.n_dates == 5
    # All dates share one padded shape.
    assert problems.qp.q.shape == (5, problems.qp.n)


def turnover_qp(P, q, n, x0, budget):
    parts = _as_parts(P, q, None, None, None, np.zeros(n), np.ones(n))
    parts["C"] = np.ones((1, n))
    parts["l"] = np.ones(1)
    parts["u"] = np.ones(1)
    parts = lift_turnover_constraint(parts, x0, budget)
    return CanonicalQP.build(
        parts["P"], parts["q"], C=parts["C"], l=parts["l"], u=parts["u"],
        lb=parts["lb"], ub=parts["ub"], dtype=jnp.float64,
    )


def test_scan_turnover_matches_serial_chain(rng):
    """Turnover-coupled dates: lax.scan carries x0 exactly as a serial
    loop updating the lifted bounds does."""
    n, n_dates, budget = 6, 4, 0.3
    Ps, qs = [], []
    for _ in range(n_dates):
        X = rng.standard_normal((60, n)) * 0.01
        Ps.append(2 * X.T @ X + 1e-6 * np.eye(n))
        qs.append(-0.02 * rng.random(n))

    # Serial reference: each date re-lifts with the previous solution.
    # Start from equal weights: a cash start (x0 = 0) is genuinely
    # infeasible under sum w = 1 with turnover budget < 1.
    w_start = np.full(n, 1.0 / n)
    x_prev = w_start
    serial_ws = []
    for d in range(n_dates):
        qp = turnover_qp(Ps[d], qs[d], n, x_prev, budget)
        sol = solve_qp(qp, TIGHT)
        assert int(sol.status) == Status.SOLVED
        x_prev = np.asarray(sol.x)[:n]
        serial_ws.append(x_prev)

    # Scan path: problems built once with x0 = 0 placeholders; the scan
    # rewrites rows [row_start, row_start + 2n) of u each step.
    qps = [turnover_qp(Ps[d], qs[d], n, np.zeros(n), budget) for d in range(n_dates)]
    batch = stack_qps(qps)
    sols = solve_scan_turnover(
        batch, n_assets=n, row_start=1, w_init=w_start, params=TIGHT,
        universes=FIXED_UNIVERSE,
    )
    for d in range(n_dates):
        assert int(sols.status[d]) == Status.SOLVED
        np.testing.assert_allclose(
            np.asarray(sols.x[d])[:n], serial_ws[d], atol=1e-5
        )
        # Turnover constraint actually binds the chain together.
        prev = serial_ws[d - 1] if d else w_start
        assert np.abs(np.asarray(sols.x[d])[:n] - prev).sum() <= budget + 1e-6


def test_zero_transaction_cost_uses_turnover_constraint(rng):
    """Regression: transaction_cost=0 + turnover constraint must apply the
    constraint lift only (a double lift produced mismatched row counts)."""
    from porqua_tpu import LeastSquares, Constraints, OptimizationData

    X = pd.DataFrame(rng.standard_normal((60, 5)) * 0.01, columns=list("ABCDE"))
    y = pd.Series(X.to_numpy() @ rng.dirichlet(np.ones(5)))
    opt = LeastSquares(transaction_cost=0, dtype=jnp.float64, **TIGHT.__dict__)
    opt.constraints = Constraints(selection=list("ABCDE"))
    opt.constraints.add_budget()
    opt.constraints.add_box("LongOnly")
    opt.constraints.add_l1("turnover", rhs=0.5, x0={a: 0.2 for a in "ABCDE"})
    opt.set_objective(OptimizationData(align=False, return_series=X, bm_series=y))
    assert opt.solve()
    w = pd.Series(opt.results["weights"])
    assert abs(w.sum() - 1.0) < 1e-6
    assert np.abs(w - 0.2).sum() <= 0.5 + 1e-6


def test_scan_l1_matches_serial_cost_chain(rng):
    """Native-prox cost-coupled dates: lax.scan carrying l1_center =
    previous solved weights matches a serial loop of prox solves."""
    from porqua_tpu.batch import solve_scan_l1

    n, n_dates, tc = 6, 4, 0.01
    qps = []
    Ps, qs = [], []
    for _ in range(n_dates):
        X = rng.standard_normal((60, n)) * 0.01
        P = 2 * X.T @ X + 1e-6 * np.eye(n)
        q = -0.02 * rng.random(n)
        Ps.append(P)
        qs.append(q)
        qps.append(CanonicalQP.build(
            P, q, C=np.ones((1, n)), l=np.ones(1), u=np.ones(1),
            lb=np.zeros(n), ub=np.ones(n), dtype=jnp.float64,
        ))

    w_start = np.full(n, 1.0 / n)

    # Serial reference: prox solve per date with the previous solution.
    x_prev = w_start
    serial_ws = []
    for d in range(n_dates):
        sol = solve_qp(
            qps[d], TIGHT,
            l1_weight=jnp.full(n, tc, jnp.float64),
            l1_center=jnp.asarray(x_prev),
        )
        assert int(sol.status) == Status.SOLVED
        x_prev = np.asarray(sol.x)[:n]
        serial_ws.append(x_prev)

    sols = solve_scan_l1(
        stack_qps(qps), n_assets=n, w_init=w_start,
        transaction_cost=tc, params=TIGHT, universes=FIXED_UNIVERSE,
    )
    for d in range(n_dates):
        assert int(sols.status[d]) == Status.SOLVED
        np.testing.assert_allclose(
            np.asarray(sols.x[d])[:n], serial_ws[d], atol=1e-5
        )


def test_scan_l1_rejects_varying_universe(rng):
    """The scan carry is positional: a date-varying selection must be
    refused, not silently mispriced."""
    from porqua_tpu.batch import solve_scan_l1

    n = 4
    qps = [CanonicalQP.build(
        np.eye(n), np.zeros(n), C=np.ones((1, n)), l=np.ones(1),
        u=np.ones(1), lb=np.zeros(n), ub=np.ones(n), dtype=jnp.float64,
    ) for _ in range(2)]
    with pytest.raises(ValueError, match="fixed asset universe"):
        solve_scan_l1(
            stack_qps(qps), n_assets=n, w_init=np.zeros(n),
            transaction_cost=0.01,
            universes=[["A", "B", "C", "D"], ["A", "B", "C", "E"]],
        )
    # The precondition is non-optional: the natural call without
    # universes must be refused at the signature (round-2 verdict), and
    # an explicit None is rejected with guidance rather than skipped.
    with pytest.raises(TypeError):
        solve_scan_l1(
            stack_qps(qps), n_assets=n, w_init=np.zeros(n),
            transaction_cost=0.01,
        )
    with pytest.raises(ValueError, match="FIXED_UNIVERSE"):
        solve_scan_l1(
            stack_qps(qps), n_assets=n, w_init=np.zeros(n),
            transaction_cost=0.01, universes=None,
        )


def test_serial_engine_with_named_backend(market, rng):
    """solver_name dispatch integrates with the full serial engine:
    the native C++ core drives a small backtest end-to-end and agrees
    with the default device solver's weights."""
    from porqua_tpu.optimization import LeastSquares

    rebdates = [str(d.date()) for d in
                pd.bdate_range("2021-01-04", periods=3, freq="21B")]

    def run(solver_name=None):
        kwargs = {} if solver_name is None else {"solver_name": solver_name}
        bs = make_service(market, rebdates, LeastSquares(**kwargs))
        bt = Backtest()
        bt.run(bs)
        return bt.strategy.get_weights_df()

    W_dev = run()
    W_native = run("native")
    assert list(W_native.index) == rebdates
    np.testing.assert_allclose(
        W_native.sum(axis=1).to_numpy(), 1.0, atol=1e-6)
    np.testing.assert_allclose(
        W_native.to_numpy(), W_dev.to_numpy(), atol=5e-5)
