"""Property-based and failure-isolation tests.

The reference has no property-based tests and aborts the whole backtest
on any per-date failure (SURVEY.md §4, §5). Here: (1) hypothesis-driven
KKT/feasibility properties over random strongly-convex QPs — the solver
must either certify optimality or report a non-SOLVED status, never
return an infeasible point labeled solved; (2) failure isolation — one
poisoned problem in a batch must not contaminate its neighbors' results
(the per-problem status vector is the batched replacement for the
reference's raised RuntimeError at ``backtest.py:193-197``).
"""

import numpy as np
import jax.numpy as jnp
import pytest

hypothesis = pytest.importorskip(
    "hypothesis",
    reason="hypothesis not installed in this environment; the "
           "property suite needs its strategies")
from hypothesis import given, settings, strategies as st  # noqa: E402

from porqua_tpu.qp.admm import SolverParams, Status
from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.solve import solve_qp, solve_qp_batch


PARAMS = SolverParams(eps_abs=1e-7, eps_rel=1e-7, max_iter=20000)


def _random_qp(seed, n, m, box_lo, box_hi):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n))
    P = A @ A.T + 0.2 * np.eye(n)
    q = rng.standard_normal(n)
    C = np.vstack([np.ones(n), rng.standard_normal((m - 1, n))]) if m else None
    l = u = None
    if m:
        l = np.concatenate([[1.0], np.full(m - 1, -3.0)])
        u = np.concatenate([[1.0], np.full(m - 1, 3.0)])
    lb = np.full(n, box_lo)
    ub = np.full(n, box_hi)
    return CanonicalQP.build(P, q, C, l, u, lb, ub, dtype=np.float64)


@settings(max_examples=25, deadline=None)
@given(
    seed=st.integers(0, 2**31 - 1),
    n=st.integers(2, 24),
    m=st.integers(0, 6),
    width=st.floats(0.5, 5.0),
)
def test_solved_points_satisfy_kkt(seed, n, m, width):
    """SOLVED implies primal feasibility + stationarity within tolerance."""
    qp = _random_qp(seed, n, m, -width, width)
    sol = solve_qp(qp, PARAMS)
    if int(sol.status) != Status.SOLVED:
        return  # non-SOLVED statuses are allowed; mislabeling is not
    x = np.asarray(sol.x)
    # Box feasibility
    assert np.all(x >= np.asarray(qp.lb) - 1e-6)
    assert np.all(x <= np.asarray(qp.ub) + 1e-6)
    # Row feasibility
    if qp.m:
        Cx = np.asarray(qp.C) @ x
        assert np.all(Cx >= np.asarray(qp.l) - 1e-5)
        assert np.all(Cx <= np.asarray(qp.u) + 1e-5)
    # Stationarity: P x + q + C'y + mu ~ 0
    grad = (np.asarray(qp.P) @ x + np.asarray(qp.q)
            + np.asarray(qp.C).T @ np.asarray(sol.y) + np.asarray(sol.mu))
    scale = max(1.0, float(np.abs(np.asarray(qp.q)).max()))
    assert float(np.abs(grad).max()) <= 1e-4 * scale


@settings(max_examples=15, deadline=None)
@given(seed=st.integers(0, 2**31 - 1), n=st.integers(2, 16))
def test_unconstrained_matches_linear_solve(seed, n):
    """With no active constraints the QP is a linear system."""
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n))
    P = A @ A.T + 0.5 * np.eye(n)
    q = rng.standard_normal(n)
    qp = CanonicalQP.build(P, q, dtype=np.float64)  # unbounded box, no rows
    sol = solve_qp(qp, PARAMS)
    assert int(sol.status) == Status.SOLVED
    x_exact = np.linalg.solve(P, -q)
    np.testing.assert_allclose(np.asarray(sol.x), x_exact, atol=1e-5, rtol=1e-5)


@settings(max_examples=15, deadline=None)
@given(seed=st.integers(0, 2**31 - 1), pad=st.integers(1, 9))
def test_padding_neutrality(seed, pad):
    """Solving a padded problem returns the unpadded problem's solution."""
    qp = _random_qp(seed, 8, 3, 0.0, 1.0)
    rng_n, rng_m = 8 + pad, 3 + 2 * pad
    qp_pad = _random_qp(seed, 8, 3, 0.0, 1.0)  # same problem...
    # ...rebuilt with explicit padding targets
    P = np.asarray(qp.P)[:8, :8]
    qp_pad = CanonicalQP.build(
        P, np.asarray(qp.q)[:8], np.asarray(qp.C)[:3, :8],
        np.asarray(qp.l)[:3], np.asarray(qp.u)[:3],
        np.asarray(qp.lb)[:8], np.asarray(qp.ub)[:8],
        n_max=rng_n, m_max=rng_m, dtype=np.float64,
    )
    a = solve_qp(qp, PARAMS)
    b = solve_qp(qp_pad, PARAMS)
    assert int(a.status) == int(b.status)
    np.testing.assert_allclose(
        np.asarray(b.x)[:8], np.asarray(a.x), atol=1e-6
    )
    assert float(np.abs(np.asarray(b.x)[8:]).max(initial=0.0)) == 0.0


class TestFailureIsolation:
    def test_poisoned_problem_does_not_contaminate_batch(self, rng):
        """NaN data in one problem: that problem fails, neighbors solve."""
        qps = [_random_qp(s, 10, 3, -2.0, 2.0) for s in (1, 2, 3)]
        poisoned = qps[1]._replace(q=jnp.full(10, jnp.nan, jnp.float64))
        batch = stack_qps([qps[0], poisoned, qps[2]])
        sols = solve_qp_batch(batch, PARAMS)
        status = np.asarray(sols.status)
        assert status[0] == Status.SOLVED
        assert status[2] == Status.SOLVED
        assert status[1] != Status.SOLVED
        clean = solve_qp_batch(stack_qps([qps[0], qps[2]]), PARAMS)
        np.testing.assert_allclose(
            np.asarray(sols.x[0]), np.asarray(clean.x[0]), atol=1e-9
        )
        np.testing.assert_allclose(
            np.asarray(sols.x[2]), np.asarray(clean.x[1]), atol=1e-9
        )

    def test_infeasible_problem_in_batch_is_flagged(self, rng):
        """A genuinely infeasible date reports a certificate, not garbage."""
        good = _random_qp(11, 8, 3, 0.0, 1.0)
        n = 8
        bad = CanonicalQP.build(
            np.eye(n), np.zeros(n),
            np.vstack([np.ones(n), np.ones(n)]),
            np.array([1.0, -np.inf]), np.array([1.0, -1.0]),
            np.zeros(n), np.ones(n), m_max=3, dtype=np.float64,
        )
        sols = solve_qp_batch(stack_qps([good, bad]), PARAMS)
        status = np.asarray(sols.status)
        assert status[0] == Status.SOLVED
        assert status[1] in (Status.PRIMAL_INFEASIBLE, Status.MAX_ITER)


@settings(deadline=None, max_examples=12)
@given(seed=st.integers(0, 2**31 - 1), n=st.integers(6, 28),
       extra=st.integers(4, 24))
def test_factored_scaling_solution_parity_property(seed, n, extra):
    """Property (round 4): for any OVERDETERMINED factored tracking
    problem (T > n, so the optimum is unique — an underdetermined
    window has a whole optimal face where two exact solvers may
    legitimately land apart), the factor-derived Jacobi scaling must
    land on the same optimum as Ruiz equilibration — the two modes
    differ only by the diagonal change of variables, which the unscale
    undoes exactly."""
    import dataclasses

    from porqua_tpu.tracking import build_tracking_qp

    T = n + extra
    rng = np.random.default_rng(seed)
    X = jnp.asarray(rng.standard_normal((T, n)) * 0.01, jnp.float64)
    y = jnp.asarray(
        np.asarray(X) @ rng.dirichlet(np.ones(n))
        + 0.001 * rng.standard_normal(T), jnp.float64)
    qp = build_tracking_qp(X, y)
    base = SolverParams(max_iter=8000, eps_abs=1e-9, eps_rel=1e-9,
                        linsolve="woodbury", woodbury_refine=1)
    ref = solve_qp(qp, base)
    got = solve_qp(qp, dataclasses.replace(base, scaling_mode="factored"))
    assert bool(ref.found) and bool(got.found)
    np.testing.assert_allclose(np.asarray(got.x), np.asarray(ref.x),
                               atol=5e-7)


def test_scan_l1_accepts_headline_config():
    """The turnover-coupled scan engine must run under the full TPU
    headline config (woodbury + factored scaling) and agree with the
    default-config chain — the scan carries warm starts and L1 centers
    across dates, which must survive both code paths."""
    import dataclasses

    import jax

    from porqua_tpu.batch import FIXED_UNIVERSE, solve_scan_l1
    from porqua_tpu.tracking import build_tracking_qp, synthetic_universe

    Xs, ys = synthetic_universe(jax.random.PRNGKey(3), n_dates=5,
                                window=40, n_assets=16,
                                dtype=jnp.float64)
    qps = jax.vmap(build_tracking_qp)(Xs, ys)
    w0 = jnp.full((16,), 1.0 / 16, jnp.float64)
    base = SolverParams(max_iter=8000, eps_abs=1e-9, eps_rel=1e-9)
    head = dataclasses.replace(base, linsolve="woodbury",
                               woodbury_refine=1,
                               scaling_mode="factored")
    ref = solve_scan_l1(qps, 16, w0, 0.002, base,
                        universes=FIXED_UNIVERSE)
    got = solve_scan_l1(qps, 16, w0, 0.002, head,
                        universes=FIXED_UNIVERSE)
    assert np.all(np.asarray(ref.status) == 1)
    assert np.all(np.asarray(got.status) == 1)
    np.testing.assert_allclose(np.asarray(got.x), np.asarray(ref.x),
                               atol=5e-7)


@settings(max_examples=10, deadline=None)
@given(seed=st.integers(0, 10_000),
       n=st.integers(6, 24),
       m=st.integers(1, 5))
def test_halpern_matches_plain_admm_optimum(seed, n, m):
    """Round 5: restarted Halpern anchoring is an acceleration of the
    SAME fixed-point iteration — on strongly convex problems (unique
    optimum) it must land where the plain averaged iteration lands,
    for any random QP, including with a native L1 term in the
    objective (the LAD prox pattern)."""
    import dataclasses

    qp = _random_qp(seed, n, m, -2.0, 2.0)
    rng = np.random.default_rng(seed + 1)
    l1w = jnp.asarray(np.where(rng.random(qp.n) < 0.5, 0.3, 0.0))
    l1c = jnp.asarray(rng.standard_normal(qp.n) * 0.1)

    plain = solve_qp(qp, PARAMS, l1_weight=l1w, l1_center=l1c)
    hal = solve_qp(
        qp,
        dataclasses.replace(PARAMS, halpern=True, check_interval=100),
        l1_weight=l1w, l1_center=l1c)
    assert int(plain.status) == Status.SOLVED
    assert int(hal.status) == Status.SOLVED
    np.testing.assert_allclose(np.asarray(hal.x), np.asarray(plain.x),
                               atol=5e-6)
