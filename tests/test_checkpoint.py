"""Checkpoint/resume tests: chunked backtest persistence and warm-start
resume (SURVEY.md §5 "Checkpoint / resume" — the capability the
reference's pickle-only persistence lacks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from porqua_tpu.checkpoint import (
    CheckpointManager,
    load_solution,
    run_batch_checkpointed,
    save_solution,
)
from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.solve import SolverParams, solve_qp_batch


def make_market_service(optimization, *, with_bm=False, seed=7,
                        n_assets=6, n_days=400, every=50, k_dates=5,
                        box_upper=0.5):
    """One copy of the BacktestService wiring for the checkpoint tests
    — strategies and data extras vary per test, the builder plumbing
    must not drift between them."""
    import pandas as pd

    from porqua_tpu.backtest import BacktestService
    from porqua_tpu.builders import (
        OptimizationItemBuilder,
        SelectionItemBuilder,
        bibfn_bm_series,
        bibfn_box_constraints,
        bibfn_budget_constraint,
        bibfn_return_series,
        bibfn_selection_data,
    )

    rng = np.random.default_rng(seed)
    dates = pd.bdate_range("2020-01-01", periods=n_days)
    X = pd.DataFrame(rng.standard_normal((n_days, n_assets)) * 0.01,
                     index=dates,
                     columns=[f"A{i}" for i in range(n_assets)])
    data = {"return_series": X}
    opt_builders = {
        "returns": OptimizationItemBuilder(bibfn=bibfn_return_series,
                                           width=100),
        "budget": OptimizationItemBuilder(bibfn=bibfn_budget_constraint,
                                          budget=1),
        "box": OptimizationItemBuilder(bibfn=bibfn_box_constraints,
                                       upper=box_upper),
    }
    if with_bm:
        data["bm_series"] = pd.DataFrame(
            {"bm": X.to_numpy() @ rng.dirichlet(np.ones(n_assets))},
            index=dates)
        opt_builders["bm"] = OptimizationItemBuilder(
            bibfn=bibfn_bm_series, width=100, align=True)
    rebdates = [str(d.date()) for d in dates[150::every][:k_dates]]
    return BacktestService(
        data=data,
        selection_item_builders={
            "data": SelectionItemBuilder(bibfn=bibfn_selection_data)},
        optimization_item_builders=opt_builders,
        optimization=optimization,
        settings={"rebdates": rebdates, "quiet": True})


def _random_batch(rng, n_problems=6, n=10, m=3):
    qps = []
    for _ in range(n_problems):
        A = rng.standard_normal((n, n))
        P = A @ A.T + 0.5 * np.eye(n)
        q = rng.standard_normal(n)
        C = np.vstack([np.ones(n), rng.standard_normal((m - 1, n))])
        l = np.concatenate([[1.0], np.full(m - 1, -2.0)])
        u = np.concatenate([[1.0], np.full(m - 1, 2.0)])
        qps.append(CanonicalQP.build(P, q, C, l, u,
                                     np.full(n, -3.0), np.full(n, 3.0),
                                     dtype=np.float64))
    return stack_qps(qps)


class TestSolutionSerialization:
    def test_roundtrip(self, rng, tmp_path):
        qp = _random_batch(rng)
        sol = solve_qp_batch(qp, SolverParams())
        path = str(tmp_path / "sol.npz")
        save_solution(path, sol)
        loaded = load_solution(path)
        for f in sol._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(sol, f)), np.asarray(getattr(loaded, f))
            )


class TestCheckpointManager:
    def test_chunk_accounting(self, rng, tmp_path):
        qp = _random_batch(rng, n_problems=5)
        sol = solve_qp_batch(qp, SolverParams())
        params = SolverParams()
        mgr = CheckpointManager.create(
            str(tmp_path / "run"), [f"d{i}" for i in range(5)], 2, params
        )
        assert mgr.n_chunks == 3
        assert mgr.completed_chunks() == 0
        one = jax.tree.map(lambda a: a[:2], sol)
        mgr.save_chunk(0, one)
        assert mgr.completed_chunks() == 1
        # A gap must stop the resume scan.
        mgr.save_chunk(2, jax.tree.map(lambda a: a[4:5], sol))
        assert mgr.completed_chunks() == 1

    def test_param_mismatch_rejected(self, tmp_path):
        d = str(tmp_path / "run")
        CheckpointManager.create(d, ["a", "b"], 1, SolverParams())
        with pytest.raises(ValueError, match="different run"):
            CheckpointManager.create(d, ["a", "b"], 1,
                                     SolverParams(eps_abs=1e-3))

    def test_dtype_and_l1_mismatch_rejected(self, tmp_path):
        """ADVICE: resuming with a different dtype (or a changed l1
        configuration) must not silently mix chunks of one run."""
        d = str(tmp_path / "run")
        CheckpointManager.create(d, ["a", "b"], 1, SolverParams(),
                                 dtype=jnp.float32)
        with pytest.raises(ValueError, match="different run"):
            CheckpointManager.create(d, ["a", "b"], 1, SolverParams(),
                                     dtype=jnp.float64)
        d2 = str(tmp_path / "run2")
        CheckpointManager.create(d2, ["a", "b"], 1, SolverParams(),
                                 dtype=jnp.float32, has_l1=False)
        with pytest.raises(ValueError, match="different run"):
            CheckpointManager.create(d2, ["a", "b"], 1, SolverParams(),
                                     dtype=jnp.float32, has_l1=True)

    def test_timestamp_rebdates_serializable(self, tmp_path):
        """Non-string rebdates (pandas Timestamps) must be coerced, not
        crash json.dump on first save."""
        import pandas as pd

        dates = list(pd.bdate_range("2020-01-01", periods=3))
        mgr = CheckpointManager.create(
            str(tmp_path / "run"), dates, 2, SolverParams())
        assert all(isinstance(d, str) for d in mgr.rebdates)


class TestRunBatchCheckpointed:
    def _make_service(self):
        from porqua_tpu.optimization import QEQW

        return make_market_service(QEQW())

    def test_resume_matches_fresh(self, tmp_path):
        """A run interrupted after chunk 0 must finish to the same
        weights as an uninterrupted run."""
        params = SolverParams(max_iter=2000)

        bs = self._make_service()
        fresh = run_batch_checkpointed(
            bs, str(tmp_path / "fresh"), chunk_size=2, params=params
        )
        assert fresh.output["checkpoint"]["resumed_chunks"] == 0

        # Simulate an interrupted run: only chunk 0 present.
        import os
        import shutil
        resume_dir = str(tmp_path / "resume")
        os.makedirs(resume_dir)
        shutil.copy(os.path.join(str(tmp_path / "fresh"), "manifest.json"),
                    os.path.join(resume_dir, "manifest.json"))
        shutil.copy(os.path.join(str(tmp_path / "fresh"), "chunk_0000.npz"),
                    os.path.join(resume_dir, "chunk_0000.npz"))

        bs2 = self._make_service()
        resumed = run_batch_checkpointed(
            bs2, resume_dir, chunk_size=2, params=params
        )
        assert resumed.output["checkpoint"]["resumed_chunks"] == 1

        wf = fresh.strategy.get_weights_df()
        wr = resumed.strategy.get_weights_df()
        np.testing.assert_allclose(wf.values, wr.values, atol=1e-6)


def test_checkpointed_default_params_match_run_batch(tmp_path):
    """Round 5: with params=None, run_batch_checkpointed must derive
    the SAME strategy-resolved solver configuration as run_batch — for
    LAD that is the LP-prox overlay (halpern + fixed rho + dtype-aware
    eps); a bare SolverParams() default here previously ran the
    adaptive-rho config documented as never converging on the LP."""
    import pandas as pd

    from porqua_tpu.batch import run_batch
    from porqua_tpu.optimization import LAD

    def service():
        return make_market_service(LAD(), with_bm=True, seed=9,
                                   every=60, k_dates=4, box_upper=1.0)

    rebdates = service().settings["rebdates"]
    ck = run_batch_checkpointed(service(), str(tmp_path / "ck"),
                                chunk_size=2)
    ref = run_batch(service())
    # Same derived config -> same convergence behavior (not the 40k
    # adaptive-rho stall). Weights agree to f32-LP localization, not
    # solver epsilon: the checkpointed path warm-starts each chunk
    # from the previous chunk's endpoint while run_batch solves dates
    # independently, and two eps=1e-4 f32 solves of a near-degenerate
    # LP from different starts land ~1e-4 apart (measured 1.8e-4).
    assert int(np.max(ck.output["batch"]["iters"])) < 20000
    for date in rebdates:
        wc = pd.Series(ck.strategy.get_weights(date))
        wr = pd.Series(ref.strategy.get_weights(date))
        np.testing.assert_allclose(wc[wr.index], wr, atol=1e-3,
                                   err_msg=date)
