"""Checkpoint/resume tests: chunked backtest persistence and warm-start
resume (SURVEY.md §5 "Checkpoint / resume" — the capability the
reference's pickle-only persistence lacks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from porqua_tpu.checkpoint import (
    CheckpointManager,
    load_solution,
    run_batch_checkpointed,
    save_solution,
)
from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.solve import SolverParams, solve_qp_batch


def _random_batch(rng, n_problems=6, n=10, m=3):
    qps = []
    for _ in range(n_problems):
        A = rng.standard_normal((n, n))
        P = A @ A.T + 0.5 * np.eye(n)
        q = rng.standard_normal(n)
        C = np.vstack([np.ones(n), rng.standard_normal((m - 1, n))])
        l = np.concatenate([[1.0], np.full(m - 1, -2.0)])
        u = np.concatenate([[1.0], np.full(m - 1, 2.0)])
        qps.append(CanonicalQP.build(P, q, C, l, u,
                                     np.full(n, -3.0), np.full(n, 3.0),
                                     dtype=np.float64))
    return stack_qps(qps)


class TestSolutionSerialization:
    def test_roundtrip(self, rng, tmp_path):
        qp = _random_batch(rng)
        sol = solve_qp_batch(qp, SolverParams())
        path = str(tmp_path / "sol.npz")
        save_solution(path, sol)
        loaded = load_solution(path)
        for f in sol._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(sol, f)), np.asarray(getattr(loaded, f))
            )


class TestCheckpointManager:
    def test_chunk_accounting(self, rng, tmp_path):
        qp = _random_batch(rng, n_problems=5)
        sol = solve_qp_batch(qp, SolverParams())
        params = SolverParams()
        mgr = CheckpointManager.create(
            str(tmp_path / "run"), [f"d{i}" for i in range(5)], 2, params
        )
        assert mgr.n_chunks == 3
        assert mgr.completed_chunks() == 0
        one = jax.tree.map(lambda a: a[:2], sol)
        mgr.save_chunk(0, one)
        assert mgr.completed_chunks() == 1
        # A gap must stop the resume scan.
        mgr.save_chunk(2, jax.tree.map(lambda a: a[4:5], sol))
        assert mgr.completed_chunks() == 1

    def test_param_mismatch_rejected(self, tmp_path):
        d = str(tmp_path / "run")
        CheckpointManager.create(d, ["a", "b"], 1, SolverParams())
        with pytest.raises(ValueError, match="different run"):
            CheckpointManager.create(d, ["a", "b"], 1,
                                     SolverParams(eps_abs=1e-3))

    def test_dtype_and_l1_mismatch_rejected(self, tmp_path):
        """ADVICE: resuming with a different dtype (or a changed l1
        configuration) must not silently mix chunks of one run."""
        d = str(tmp_path / "run")
        CheckpointManager.create(d, ["a", "b"], 1, SolverParams(),
                                 dtype=jnp.float32)
        with pytest.raises(ValueError, match="different run"):
            CheckpointManager.create(d, ["a", "b"], 1, SolverParams(),
                                     dtype=jnp.float64)
        d2 = str(tmp_path / "run2")
        CheckpointManager.create(d2, ["a", "b"], 1, SolverParams(),
                                 dtype=jnp.float32, has_l1=False)
        with pytest.raises(ValueError, match="different run"):
            CheckpointManager.create(d2, ["a", "b"], 1, SolverParams(),
                                     dtype=jnp.float32, has_l1=True)

    def test_timestamp_rebdates_serializable(self, tmp_path):
        """Non-string rebdates (pandas Timestamps) must be coerced, not
        crash json.dump on first save."""
        import pandas as pd

        dates = list(pd.bdate_range("2020-01-01", periods=3))
        mgr = CheckpointManager.create(
            str(tmp_path / "run"), dates, 2, SolverParams())
        assert all(isinstance(d, str) for d in mgr.rebdates)


class TestRunBatchCheckpointed:
    def _make_service(self):
        import pandas as pd

        from porqua_tpu.backtest import BacktestService
        from porqua_tpu.builders import (
            OptimizationItemBuilder,
            SelectionItemBuilder,
            bibfn_box_constraints,
            bibfn_budget_constraint,
            bibfn_return_series,
            bibfn_selection_data,
        )
        from porqua_tpu.optimization import QEQW

        rng = np.random.default_rng(7)
        n_assets, n_days = 6, 400
        dates = pd.bdate_range("2020-01-01", periods=n_days)
        X = pd.DataFrame(
            rng.standard_normal((n_days, n_assets)) * 0.01,
            index=dates,
            columns=[f"A{i}" for i in range(n_assets)],
        )
        data = {"return_series": X}
        rebdates = [str(d.date()) for d in dates[150::50][:5]]
        return BacktestService(
            data=data,
            selection_item_builders={
                "data": SelectionItemBuilder(bibfn=bibfn_selection_data),
            },
            optimization_item_builders={
                "returns": OptimizationItemBuilder(
                    bibfn=bibfn_return_series, width=100),
                "budget": OptimizationItemBuilder(
                    bibfn=bibfn_budget_constraint, budget=1),
                "box": OptimizationItemBuilder(
                    bibfn=bibfn_box_constraints, upper=0.5),
            },
            optimization=QEQW(),
            settings={"rebdates": rebdates, "quiet": True},
        )

    def test_resume_matches_fresh(self, tmp_path):
        """A run interrupted after chunk 0 must finish to the same
        weights as an uninterrupted run."""
        params = SolverParams(max_iter=2000)

        bs = self._make_service()
        fresh = run_batch_checkpointed(
            bs, str(tmp_path / "fresh"), chunk_size=2, params=params
        )
        assert fresh.output["checkpoint"]["resumed_chunks"] == 0

        # Simulate an interrupted run: only chunk 0 present.
        import os
        import shutil
        resume_dir = str(tmp_path / "resume")
        os.makedirs(resume_dir)
        shutil.copy(os.path.join(str(tmp_path / "fresh"), "manifest.json"),
                    os.path.join(resume_dir, "manifest.json"))
        shutil.copy(os.path.join(str(tmp_path / "fresh"), "chunk_0000.npz"),
                    os.path.join(resume_dir, "chunk_0000.npz"))

        bs2 = self._make_service()
        resumed = run_batch_checkpointed(
            bs2, resume_dir, chunk_size=2, params=params
        )
        assert resumed.output["checkpoint"]["resumed_chunks"] == 1

        wf = fresh.strategy.get_weights_df()
        wr = resumed.strategy.get_weights_df()
        np.testing.assert_allclose(wf.values, wr.values, atol=1e-6)
