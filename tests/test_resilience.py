"""Resilience plane (porqua_tpu.resilience): deterministic fault
injection, retry/hedging recovery policies, crash-resume backtests.

Three layers of coverage: (1) the injector itself — seam/kind typing,
per-spec counters, seeded deterministic replay, exclusive install;
(2) the recovery paths it drives — breaker-riding device-fault retry,
NaN-validation withholding, deadline give-up, idempotent resubmission
by request id, hedging, the injectable breaker clock; (3) crash-resume
bit-parity for the turnover-coupled scan backtest (a run killed at a
seeded segment boundary and resumed equals an uninterrupted run, bit
for bit). The GC007 guard lint and GC104 jaxpr-identity contract are
exercised here too (seeded violation + shipped-tree pass).
"""

import os
import textwrap

import numpy as np
import pytest

import jax.numpy as jnp

from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.solve import SolverParams
from porqua_tpu.resilience import faults
from porqua_tpu.resilience.retry import RetryManager, RetryPolicy, validate_result
from porqua_tpu.serve import BucketLadder, DeviceHealth, ServeMetrics, SolveService

PARAMS = SolverParams(max_iter=500, eps_abs=1e-5, eps_rel=1e-5,
                      polish=False, check_interval=25)
LADDER = BucketLadder(n_rungs=(8, 16), m_rungs=(4, 8))


def make_qp(n=6, m=2, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * n, n))
    P = A.T @ A / (2 * n) + np.eye(n)
    q = rng.standard_normal(n)
    C = np.concatenate([np.ones((1, n)), rng.standard_normal((m - 1, n))])
    return CanonicalQP.build(P, q, C=C, l=np.full(m, -1.0), u=np.ones(m),
                             lb=np.zeros(n), ub=np.ones(n))


def service(**kw):
    kw.setdefault("params", PARAMS)
    kw.setdefault("ladder", LADDER)
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5.0)
    return SolveService(**kw)


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    """A test that leaks its injector would perturb every later test
    in the process — fail loudly instead."""
    assert not faults.enabled(), "fault injector leaked into this test"
    yield
    leaked = faults.enabled()
    faults.uninstall()
    assert not leaked, "test leaked an installed fault injector"


# ---------------------------------------------------------------------------
# injector core
# ---------------------------------------------------------------------------

def test_fault_spec_dsl_typing():
    mk = faults.FaultSpec.make
    with pytest.raises(ValueError, match="unknown seam"):
        mk("serve.nonsense", "device_lost")
    with pytest.raises(ValueError, match="unknown fault kind"):
        mk("serve.dispatch", "gremlins")
    with pytest.raises(ValueError, match="cannot target seam"):
        mk("serve.admission", "device_lost")
    with pytest.raises(ValueError, match="count"):
        mk("serve.dispatch", "device_lost", count=0)
    with pytest.raises(ValueError, match="p must be"):
        mk("serve.dispatch", "device_lost", p=0.0)


def test_injector_start_count_and_exhaustion():
    sc = faults.Scenario("t", (faults.FaultSpec.make(
        "serve.result", "nan_lanes", start=1, count=2, lanes=3),))
    inj = faults.FaultInjector(sc)
    hits = [inj.fire("serve.result") for _ in range(5)]
    # hit 0 skipped (start=1), hits 1-2 fire, 3-4 quiet (count spent)
    assert [h is None for h in hits] == [True, False, False, True, True]
    assert hits[1].kind == "nan_lanes" and hits[1].args["lanes"] == 3
    assert inj.fires() == 2 and inj.fires("serve.result") == 2
    assert inj.exhausted()
    assert [e["hit"] for e in inj.log()] == [1, 2]


def test_injector_seeded_replay_is_deterministic():
    """Same scenario seed -> identical fire sequence (the p<1 draws
    come from a per-spec stream keyed by the rule identity alone)."""
    def run(seed):
        sc = faults.Scenario("t", (faults.FaultSpec.make(
            "serve.admission", "clock_skew", count=50, p=0.5,
            skew_s=1.0),), seed=seed)
        inj = faults.FaultInjector(sc)
        return [inj.fire("serve.admission") is not None
                for _ in range(64)]

    assert run(7) == run(7)
    assert run(7) != run(8)  # 2^-64 flake odds


def test_install_is_exclusive_and_context_managed():
    sc = faults.Scenario("a", (faults.FaultSpec.make(
        "serve.dispatch", "device_lost"),))
    with faults.active(sc):
        assert faults.enabled()
        with pytest.raises(RuntimeError, match="already installed"):
            faults.install(faults.FaultInjector(sc))
    assert not faults.enabled()
    assert faults.fire("serve.dispatch") is None  # disabled = no-op


def test_raising_kinds_raise():
    with faults.active(faults.Scenario("a", (
            faults.FaultSpec.make("serve.dispatch", "device_lost"),
            faults.FaultSpec.make("backtest.chunk", "crash")))):
        with pytest.raises(faults.InjectedFault):
            faults.fire("serve.dispatch")
        with pytest.raises(faults.InjectedCrash):
            faults.fire("backtest.chunk")
    # InjectedCrash must NOT be containable by `except Exception` —
    # that is the whole point of modeling a SIGKILL with it.
    assert not issubclass(faults.InjectedCrash, Exception)


def test_fault_clock():
    clock = faults.FaultClock(start=10.0)
    assert clock() == 10.0
    assert clock.advance(2.5) == 12.5
    assert clock() == 12.5


# ---------------------------------------------------------------------------
# recovery policies
# ---------------------------------------------------------------------------

def test_validate_result_gate():
    class R:
        def __init__(self, x, prim=0.0, dual=0.0, obj=1.0):
            self.x, self.prim_res, self.dual_res, self.obj_val = \
                x, prim, dual, obj

    assert validate_result(R(np.ones(3))) is None
    assert "primal" in validate_result(R(np.array([1.0, np.nan])))
    assert "prim_res" in validate_result(R(np.ones(2), prim=np.inf))


def test_backoff_jitter_bounded_and_growing():
    pol = RetryPolicy(backoff_base_s=0.1, backoff_mult=2.0, jitter=0.5)
    rng = np.random.default_rng(0)
    d1 = [pol.backoff_s(1, rng) for _ in range(100)]
    d3 = [pol.backoff_s(3, rng) for _ in range(100)]
    assert all(0.05 <= d <= 0.15 for d in d1)
    assert all(0.2 <= d <= 0.6 for d in d3)
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.0)


def test_retry_recovers_injected_device_fault():
    """device_lost faults exhaust the dispatch containment (single-
    device health: nothing to fall to), the request-level retry
    re-drives the request, and the caller gets the right answer —
    counted as retries + one resumed request."""
    import jax

    dev = jax.devices("cpu")[0]
    health = DeviceHealth(primary=dev, fallback=dev,
                          failure_threshold=2)
    with service(health=health,
                 retry=RetryPolicy(max_attempts=4,
                                   backoff_base_s=0.01)) as svc:
        with faults.active(faults.Scenario("dl", (
                faults.FaultSpec.make("serve.dispatch", "device_lost",
                                      count=3),)), metrics=svc.metrics):
            res = svc.solve(make_qp(seed=1), timeout=120)
    assert res.found
    snap = svc.snapshot()
    assert snap["retries"] >= 1
    assert snap["resumed_requests"] == 1
    assert snap["dispatch_failures"] == 3
    assert snap["retry_giveups"] == 0


def test_retry_gives_up_after_max_attempts():
    import jax

    from porqua_tpu.serve import SolveError

    dev = jax.devices("cpu")[0]
    health = DeviceHealth(primary=dev, fallback=dev,
                          failure_threshold=2)
    with service(health=health,
                 retry=RetryPolicy(max_attempts=2,
                                   backoff_base_s=0.01)) as svc:
        with faults.active(faults.Scenario("dl", (
                faults.FaultSpec.make("serve.dispatch", "device_lost",
                                      count=50),))):
            with pytest.raises(SolveError):
                svc.solve(make_qp(seed=2), timeout=120)
    snap = svc.snapshot()
    assert snap["retry_giveups"] == 1
    assert snap["completed"] == 0


def test_nan_lane_corruption_withheld_and_retried():
    """An injected serve.result NaN corruption must never reach the
    caller: validation withholds it, the retry resubmits, the second
    attempt is clean."""
    with service(retry=RetryPolicy(max_attempts=3,
                                   backoff_base_s=0.01)) as svc:
        with faults.active(faults.Scenario("nan", (
                faults.FaultSpec.make("serve.result", "nan_lanes",
                                      count=1, lanes=1),)),
                           metrics=svc.metrics):
            res = svc.solve(make_qp(seed=3), timeout=120)
    assert res.found and np.all(np.isfinite(res.x))
    snap = svc.snapshot()
    assert snap["validation_failures"] == 1
    assert snap["retries"] == 1
    assert snap["resumed_requests"] == 1


def test_idempotent_resubmission_no_double_resolve():
    """One request id, one future, one resolution: resubmitting a
    RESOLVED id returns the same ticket/result and moves no counters;
    resubmitting an in-flight id returns the same future."""
    qp = make_qp(seed=4)
    with service(retry=RetryPolicy()) as svc:
        t1 = svc.submit(qp, request_id="r-1")
        t1b = svc.submit(qp, request_id="r-1")   # in flight: same future
        assert t1b.future is t1.future
        res1 = svc.result(t1, timeout=120)
        base = svc.snapshot()

        t2 = svc.submit(qp, request_id="r-1")    # resolved: same future
        assert t2.future is t1.future
        assert svc.result(t2, timeout=1) is res1
        snap = svc.snapshot()
        assert snap["submitted"] == base["submitted"]
        assert snap["completed"] == base["completed"]
        assert snap["resumed_requests"] == base["resumed_requests"]
        assert svc._retry.entry_stats("r-1")["attempts"] == 1

        # A different id is a different request (no false dedupe).
        res2 = svc.result(svc.submit(qp, request_id="r-2"), timeout=120)
        assert res2 is not res1
    assert svc.snapshot()["completed"] == base["completed"] + 1


def test_request_id_without_retry_policy_raises():
    with service() as svc:
        with pytest.raises(ValueError, match="retry policy"):
            svc.submit(make_qp(), request_id="r-1")


def test_submit_unstarted_service_raises_with_retry_policy():
    """The retry path must fail an unstarted submit as loudly as the
    raw path: swallowed into a retryable attempt, the RuntimeError
    would schedule onto a never-started timer thread and the caller's
    future would simply never resolve."""
    svc = service(retry=RetryPolicy())
    try:
        with pytest.raises(RuntimeError, match="not started"):
            svc.submit(make_qp())
    finally:
        svc.stop()


def test_stop_fails_unresolved_retry_futures():
    """stop() abandons scheduled retries — the affected futures must
    fail immediately (retry_giveups, reason=stopped), not leave the
    caller blocked forever on a timer that will never fire."""
    import time as _time

    import jax

    from porqua_tpu.serve import SolveError

    dev = jax.devices("cpu")[0]
    health = DeviceHealth(primary=dev, fallback=dev,
                          failure_threshold=2)
    svc = service(health=health,
                  retry=RetryPolicy(max_attempts=10,
                                    backoff_base_s=30.0)).start()
    try:
        with faults.active(faults.Scenario("dl", (
                faults.FaultSpec.make("serve.dispatch", "device_lost",
                                      count=50),))):
            ticket = svc.submit(make_qp(seed=7))
            deadline = _time.monotonic() + 30
            while (svc.snapshot()["retries"] < 1
                   and _time.monotonic() < deadline):
                _time.sleep(0.02)
            assert svc.snapshot()["retries"] >= 1
            svc.stop()
            with pytest.raises(SolveError, match="stopped"):
                svc.result(ticket, timeout=5)
    finally:
        svc.stop()
    assert svc.snapshot()["retry_giveups"] == 1


class _FakeRawService:
    """Raw-submit stand-in: records each inner attempt's future so a
    test resolves attempts by hand without a real dispatch loop."""

    def __init__(self):
        self.inner = []

    def _submit_raw(self, qp, deadline_s=None, warm_key=None,
                    timeout=None, tenant=None):
        import time as _time

        from concurrent.futures import Future

        from porqua_tpu.serve.service import Ticket

        fut = Future()
        self.inner.append((qp, fut))
        return Ticket(future=fut, submitted=_time.monotonic())


def _fake_solution():
    import types

    return types.SimpleNamespace(x=np.ones(3), prim_res=0.0,
                                 dual_res=0.0, obj_val=1.0)


def test_registry_eviction_spares_inflight_entries():
    """LRU eviction must only drop RESOLVED entries: evicting an
    in-flight id would fork it (a duplicate submit registers a second
    future for the same request) and orphan the original future at
    stop(), which only fails entries still in the registry."""
    raw = _FakeRawService()
    mgr = RetryManager(raw, RetryPolicy(registry_capacity=2),
                       ServeMetrics())
    mgr.start()
    try:
        live = mgr.submit(make_qp(), request_id="live")  # stays in flight
        for i in range(3):
            t = mgr.submit(make_qp(), request_id=f"r-{i}")
            raw.inner[-1][1].set_result(_fake_solution())
            t.future.result(timeout=5)
        # "live" is the LRU-oldest, but unresolved: resubmission must
        # still dedupe onto the original future (not a fresh entry).
        assert mgr.submit(make_qp(), request_id="live").future \
            is live.future
        with mgr._lock:
            assert "live" in mgr._entries
            assert len(mgr._entries) <= 2 + 1  # capacity + the in-flight
        raw.inner[0][1].set_result(_fake_solution())
        live.future.result(timeout=5)
    finally:
        mgr.stop()


def test_resolved_entry_drops_problem_payload():
    """Resolution keeps the idempotency record (id -> future) but must
    drop the QP payload: up to registry_capacity retained problem
    matrices is real memory on real sizes, and no attempt is ever
    issued for a resolved entry."""
    raw = _FakeRawService()
    mgr = RetryManager(raw, RetryPolicy(), ServeMetrics())
    mgr.start()
    try:
        t = mgr.submit(make_qp(), request_id="rid")
        with mgr._lock:
            assert mgr._entries["rid"].qp is not None
        raw.inner[-1][1].set_result(_fake_solution())
        res = t.future.result(timeout=5)
        with mgr._lock:
            entry = mgr._entries["rid"]
            assert entry.resolved and entry.qp is None
        # The payload-free entry still dedupes to the same resolution.
        t2 = mgr.submit(make_qp(), request_id="rid")
        assert t2.future is t.future
        assert t2.future.result(timeout=1) is res
    finally:
        mgr.stop()


def test_gc007_orelse_and_negated_guard_rejected(tmp_path):
    """A fire() in the else branch of an enabled() check, or under
    `if not enabled():`, is exactly the disabled-path seam GC007
    exists to catch — the guard must be the If BODY under a
    non-negated test."""
    from porqua_tpu.analysis.lint import scan_paths

    path = tmp_path / "serve" / "bad3.py"
    path.parent.mkdir(parents=True)
    path.write_text(textwrap.dedent("""\
        from porqua_tpu.resilience import faults as _faults

        def dispatch(batch):
            if _faults.enabled():
                pass
            else:
                _faults.fire("serve.dispatch")      # orelse: flagged
            if not _faults.enabled():
                _faults.fire("serve.dispatch")      # negated: flagged
            if _faults.enabled():
                _faults.fire("serve.dispatch")      # guarded: clean
            return batch
        """))
    hits = [(f.rule, f.line) for f in scan_paths([str(path)],
                                                 rules={"GC007"})]
    assert hits == [("GC007", 7), ("GC007", 9)]


def test_hedge_fires_for_straggling_request():
    """A request still unresolved past hedge_after_s fires exactly one
    duplicate; the caller still gets exactly one (valid) result."""
    with service(max_wait_ms=250.0,
                 retry=RetryPolicy(hedge_after_s=0.04,
                                   backoff_base_s=0.01)) as svc:
        # One lone request: the batcher's age trigger holds it ~250 ms,
        # far past the hedge timer.
        res = svc.solve(make_qp(seed=5), timeout=120)
    assert res.found
    snap = svc.snapshot()
    assert snap["hedges_fired"] == 1
    assert snap["retry_giveups"] == 0


def test_breaker_reclose_on_injected_clock():
    """The breaker's open->half-open->close cycle replayed on a
    stepped FaultClock: no wall-clock waits, fully deterministic
    timing decisions (the recovery probe still runs on its thread)."""
    import jax
    import time as _time

    devices = jax.devices()
    assert len(devices) >= 2  # conftest forces 8 virtual devices
    clock = faults.FaultClock()
    probe_ok = [False]
    metrics = ServeMetrics()
    health = DeviceHealth(primary=devices[-1], fallback=devices[0],
                          probe_fn=lambda dev: probe_ok[0],
                          failure_threshold=2, probe_timeout_s=5.0,
                          recovery_interval_s=60.0, metrics=metrics,
                          clock=clock)
    assert health.record_failure(RuntimeError("boom")) is True
    assert health.record_failure(RuntimeError("boom")) is True  # trips
    assert health.degraded
    # Inside the recovery interval: no re-probe, fallback served.
    assert health.device() is health.fallback
    # Step PAST the interval on the fake clock; the next device() call
    # schedules the half-open probe. First probe fails -> re-armed.
    clock.advance(61.0)
    health.device()
    deadline = _time.monotonic() + 5.0
    while health._recovery_inflight and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert health.degraded  # probe said no; open window re-armed
    # Re-armed at the fake now: another 61 fake seconds, probe now ok.
    probe_ok[0] = True
    clock.advance(61.0)
    health.device()
    deadline = _time.monotonic() + 5.0
    while health.degraded and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert not health.degraded
    assert health.device() is health.primary


def test_probe_fail_seam_trips_breaker_without_device():
    """health.probe seam: a probe_fail directive makes the startup
    check trip the breaker with no device involvement at all."""
    import jax

    devices = jax.devices()
    metrics = ServeMetrics()
    health = DeviceHealth(primary=devices[-1], fallback=devices[0],
                          failure_threshold=2, recovery_interval_s=3600.0,
                          metrics=metrics)
    with faults.active(faults.Scenario("probe", (
            faults.FaultSpec.make("health.probe", "probe_fail",
                                  count=2),))):
        health.startup_check()
    assert health.degraded
    assert metrics.snapshot()["probe_failures"] == 2


# ---------------------------------------------------------------------------
# crash-resume bit-parity (checkpointed scan backtest)
# ---------------------------------------------------------------------------

def _w_init_sha(w_init, dtype):
    """The scan-checkpoint run-identity fingerprint for a padded
    w_init (mirrors solve_scan_l1_checkpointed's key derivation)."""
    from porqua_tpu.checkpoint import _array_fingerprint

    n = len(w_init)
    w0 = jnp.zeros(n, dtype).at[:n].set(jnp.asarray(w_init, dtype)[:n])
    return _array_fingerprint(w0)


def _scan_problem(n=6, n_dates=6, seed=11):
    rng = np.random.default_rng(seed)
    qps = []
    for _ in range(n_dates):
        X = rng.standard_normal((60, n)) * 0.01
        P = 2 * X.T @ X + 1e-6 * np.eye(n)
        q = -0.02 * rng.random(n)
        qps.append(CanonicalQP.build(
            P, q, C=np.ones((1, n)), l=np.ones(1), u=np.ones(1),
            lb=np.zeros(n), ub=np.ones(n), dtype=jnp.float64))
    return stack_qps(qps), np.full(n, 1.0 / n)


def test_scan_checkpoint_crash_resume_bit_parity(tmp_path):
    """The acceptance invariant: a scan backtest killed at a seeded
    random segment boundary and resumed from checkpoint produces
    BIT-identical results to an uninterrupted run (and both match the
    unsegmented scan exactly)."""
    from porqua_tpu.batch import FIXED_UNIVERSE, solve_scan_l1
    from porqua_tpu.checkpoint import solve_scan_l1_checkpointed

    params = SolverParams(max_iter=2000, eps_abs=1e-7, eps_rel=1e-7)
    qp, w_init = _scan_problem()
    tc, seg = 0.01, 2

    golden, info = solve_scan_l1_checkpointed(
        qp, 6, w_init, tc, str(tmp_path / "golden"), params=params,
        segment_size=seg, universes=FIXED_UNIVERSE)
    assert info["resumed_segments"] == 0
    assert info["total_segments"] == 3

    # Kill at a seeded random boundary (after that segment persisted).
    k = int(np.random.default_rng(0).integers(0, 2))
    crash = faults.Scenario("crash", (faults.FaultSpec.make(
        "backtest.chunk", "crash", start=k, count=1),))
    with faults.active(crash):
        with pytest.raises(faults.InjectedCrash):
            solve_scan_l1_checkpointed(
                qp, 6, w_init, tc, str(tmp_path / "crashed"),
                params=params, segment_size=seg,
                universes=FIXED_UNIVERSE)

    resumed, info2 = solve_scan_l1_checkpointed(
        qp, 6, w_init, tc, str(tmp_path / "crashed"), params=params,
        segment_size=seg, universes=FIXED_UNIVERSE)
    assert info2["resumed_segments"] == k + 1

    for f in golden._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(golden, f)),
            np.asarray(getattr(resumed, f)), err_msg=f)

    # And the segmented run IS the unsegmented scan, bit for bit (the
    # split executes the identical per-date step on identical values).
    uncut = solve_scan_l1(qp, 6, w_init, tc, params=params,
                          universes=FIXED_UNIVERSE)
    np.testing.assert_array_equal(np.asarray(golden.x),
                                  np.asarray(uncut.x))


def test_scan_checkpoint_requires_carry_for_resume(tmp_path):
    """A crash BETWEEN the chunk write and the carry write must roll
    that segment back (resume from an unreconstructable boundary would
    chain from the wrong state)."""
    from porqua_tpu.batch import FIXED_UNIVERSE
    from porqua_tpu.checkpoint import (
        CheckpointManager,
        solve_scan_l1_checkpointed,
    )

    params = SolverParams(max_iter=2000, eps_abs=1e-7, eps_rel=1e-7)
    qp, w_init = _scan_problem()
    d = str(tmp_path / "run")
    golden, _ = solve_scan_l1_checkpointed(
        qp, 6, w_init, 0.01, d, params=params, segment_size=2,
        universes=FIXED_UNIVERSE)

    # Re-attach to the run directory (create() on an existing manifest
    # validates the run identity and returns the manager).
    mgr = CheckpointManager.create(
        d, [str(i) for i in range(6)], 2, params, dtype=jnp.float64,
        has_l1=True,
        extra={"kind": "scan_l1", "transaction_cost": 0.01,
               "n_assets": 6,
               "w_init_sha": _w_init_sha(w_init, jnp.float64)})
    assert mgr.completed_chunks(require_carry=True) == 3
    os.remove(mgr.carry_path(1))
    assert mgr.completed_chunks(require_carry=True) == 1
    assert mgr.completed_chunks() == 3  # plain chunk scan unaffected

    resumed, info = solve_scan_l1_checkpointed(
        qp, 6, w_init, 0.01, d, params=params, segment_size=2,
        universes=FIXED_UNIVERSE)
    assert info["resumed_segments"] == 1  # rolled back to the carry
    np.testing.assert_array_equal(np.asarray(golden.x),
                                  np.asarray(resumed.x))


def test_run_batch_checkpointed_crash_seam_identity(tmp_path):
    """backtest.chunk seam in run_batch_checkpointed: an injected
    crash after chunk 0 leaves exactly the chunks-so-far on disk, and
    CheckpointManager reports them resumable."""
    from porqua_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager.create(str(tmp_path / "r"),
                                   [f"d{i}" for i in range(4)], 2,
                                   PARAMS)
    # The seam contract, minus the heavyweight BacktestService: fire
    # the seam exactly as run_batch_checkpointed does after each save.
    crash = faults.Scenario("crash", (faults.FaultSpec.make(
        "backtest.chunk", "crash", start=1, count=1),))
    with faults.active(crash):
        assert faults.fire("backtest.chunk", idx=0) is None
        with pytest.raises(faults.InjectedCrash):
            faults.fire("backtest.chunk", idx=1)


# ---------------------------------------------------------------------------
# GC007 / GC104: the guard lint and the jaxpr-identity contract
# ---------------------------------------------------------------------------

def test_gc007_unguarded_seam_detected(tmp_path):
    from porqua_tpu.analysis.lint import scan_paths

    path = tmp_path / "serve" / "bad.py"
    path.parent.mkdir(parents=True)
    path.write_text(textwrap.dedent("""\
        from porqua_tpu.resilience import faults as _faults

        def dispatch(batch):
            _faults.fire("serve.dispatch")          # unguarded: flagged
            if _faults.enabled():
                _faults.fire("serve.dispatch")      # guarded: clean
            return batch
        """))
    hits = [(f.rule, f.line) for f in scan_paths([str(path)],
                                                 rules={"GC007"})]
    assert hits == [("GC007", 4)]


def test_gc007_bare_import_forms(tmp_path):
    from porqua_tpu.analysis.lint import scan_paths

    path = tmp_path / "serve" / "bad2.py"
    path.parent.mkdir(parents=True)
    path.write_text(textwrap.dedent("""\
        from porqua_tpu.resilience.faults import enabled, fire

        def dispatch(batch):
            fire("serve.dispatch")
            if enabled():
                fire("serve.dispatch")
            return batch
        """))
    hits = [(f.rule, f.line) for f in scan_paths([str(path)],
                                                 rules={"GC007"})]
    assert hits == [("GC007", 4)]


def test_gc104_identity_contract_shipped_tree():
    """With a live injector installed over EVERY seam, the traced
    solve/serve programs must be string-identical to the bare traces —
    the machine-checked 'bit-identical when disabled' promise."""
    from porqua_tpu.analysis.contracts import check_resilience_identity

    assert check_resilience_identity() == []
