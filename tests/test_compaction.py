"""Straggler semantics for the segment-compacted batch solve and the
continuous-batching serve path.

Pins the three contracts the compaction work rests on:

* the steppable ADMM API (``admm_init`` / ``admm_segment_step``) is
  bit-identical to the fused ``admm_solve`` while_loop;
* the compacting driver returns bit-identical solutions for converged
  lanes vs the non-compacting path, retires stragglers at their
  segment budget as ``MAX_ITER`` (+ polish fallback), and scatter-back
  preserves lane order;
* the repack/step programs carry the GC101–103 jaxpr contracts (no
  host syncs or transfers) and run clean under ``PORQUA_SANITIZE=1``.

Compile-cost discipline: ONE module-scoped driver (prewarmed once —
the segment budget is a runtime operand, so every budget test reuses
the same executables) and ONE module-scoped continuous service shared
by the serve tests.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from porqua_tpu.compaction import CompactingDriver
from porqua_tpu.qp.admm import Status, admm_init, admm_segment_step, admm_solve
from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.ruiz import equilibrate
from porqua_tpu.qp.solve import SolverParams, solve_qp_batch

# Tight-eps config so the deliberately ill-conditioned lane genuinely
# straggles (and exhausts max_iter) while the clean lanes converge in
# a handful of segments.
PARAMS = SolverParams(max_iter=1000, eps_abs=1e-7, eps_rel=1e-7,
                      polish=False, check_interval=25)

N, M, B = 12, 3, 7
STRAGGLER = 3  # lane index of the ill-conditioned problem


def _ill_P(rng, n):
    """Condition number ~1e6: ADMM's fixed-point rate collapses and
    the lane runs to max_iter at tight eps."""
    d = np.logspace(-4, 2, n)
    Q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    P = Q @ np.diag(d) @ Q.T
    return (P + P.T) / 2 + 1e-6 * np.eye(n)


def _make_batch():
    rng = np.random.default_rng(0)
    qps = []
    for i in range(B):
        A = rng.standard_normal((2 * N, N))
        P = A.T @ A / (2 * N) + np.eye(N)
        if i == STRAGGLER:
            P = _ill_P(rng, N)
        qps.append(CanonicalQP.build(
            P, rng.standard_normal(N),
            C=np.concatenate([np.ones((1, N)),
                              rng.standard_normal((M - 1, N))]),
            l=np.full(M, -1.0), u=np.ones(M),
            lb=np.zeros(N), ub=np.ones(N)))
    return stack_qps(qps)


@pytest.fixture(scope="module")
def batch():
    return _make_batch()


@pytest.fixture(scope="module")
def fused(batch):
    """The non-compacting reference solve."""
    return solve_qp_batch(batch, PARAMS)


@pytest.fixture(scope="module")
def driver(batch):
    """One prewarmed driver shared by every batch-compaction test (the
    segment budget is a per-call runtime operand, not an executable
    fork)."""
    d = CompactingDriver(PARAMS)
    compiled = d.prewarm(B, N, M)
    assert compiled > 0
    return d


# ---------------------------------------------------------------------------
# steppable API
# ---------------------------------------------------------------------------

def test_segment_step_matches_admm_solve(batch):
    """A host loop over jitted admm_segment_step reproduces the fused
    while_loop bit-for-bit (same compiled segment program)."""
    qp = jax.tree.map(lambda a: a[0], batch)
    scaled, scaling = equilibrate(qp, iters=PARAMS.scaling_iters)

    @functools.partial(jax.jit, static_argnames=("params",))
    def step(carry, s, sc, params):
        return admm_segment_step(carry, s, sc, params)[0]

    @functools.partial(jax.jit, static_argnames=("params",))
    def fused_solve(s, sc, params):
        return admm_solve(s, sc, params)

    carry = jax.jit(lambda q: admm_init(q, PARAMS))(scaled)
    n_segments = 0
    while (int(carry.state.status) == Status.RUNNING
           and int(carry.state.iters) < PARAMS.max_iter):
        carry = step(carry, scaled, scaling, PARAMS)
        n_segments += 1
    assert n_segments >= 1
    ref = fused_solve(scaled, scaling, PARAMS)
    got = carry.state._replace(status=jnp.where(
        carry.state.status == Status.RUNNING, Status.MAX_ITER,
        carry.state.status).astype(jnp.int32))
    for name in ("x", "z", "w", "y", "mu", "rho_bar", "iters", "status",
                 "prim_res", "dual_res"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(ref, name)), err_msg=name)


# ---------------------------------------------------------------------------
# compacting driver
# ---------------------------------------------------------------------------

def test_compaction_bit_parity_and_lane_order(batch, fused, driver):
    """(a) + (c): converged lanes bit-identical to the non-compacting
    path, in the original lane order (scatter-back preserves it), with
    real lane-segment savings and ladder-only dispatch shapes."""
    sol, rep = driver.solve(batch)
    assert rep.compiles == 0, "prewarmed solve must not compile"

    status = np.asarray(fused.status)
    np.testing.assert_array_equal(status, np.asarray(sol.status))
    np.testing.assert_array_equal(np.asarray(fused.iters),
                                  np.asarray(sol.iters))
    x_ref, x_cmp = np.asarray(fused.x), np.asarray(sol.x)
    assert status[STRAGGLER] == Status.MAX_ITER  # the tail exists
    for i in range(B):
        if status[i] == Status.SOLVED:
            np.testing.assert_array_equal(x_ref[i], x_cmp[i],
                                          err_msg=f"lane {i}")

    # Work accounting: the straggler no longer taxes the cohort.
    assert rep.lane_segments < rep.dense_lane_segments
    assert rep.savings_vs_dense >= 0.2
    from porqua_tpu.serve.bucketing import slot_ladder

    rungs = set(slot_ladder(B))
    assert set(rep.dispatch_sizes) <= rungs
    assert list(rep.dispatch_sizes) == sorted(rep.dispatch_sizes,
                                              reverse=True)
    assert rep.max_iter_lanes == int(np.sum(status == Status.MAX_ITER))


def test_compaction_off_matches_dense_accounting(batch, fused, driver):
    """compact=False steps full width every boundary: executed ==
    batch x max-segments, and results still match the fused path."""
    sol, rep = driver.solve(batch, compact=False)
    assert rep.lane_segments == rep.dense_lane_segments
    assert set(rep.dispatch_sizes) == {B}
    np.testing.assert_array_equal(np.asarray(fused.iters),
                                  np.asarray(sol.iters))


def test_straggler_retires_at_segment_budget(batch, driver):
    """(b): with a per-lane budget the straggler retires as MAX_ITER at
    exactly budget segments — bit-identical to the fused path run with
    the equivalent max_iter — and the clean lanes are untouched."""
    budget = 16  # = 400 iterations; the clean lanes need <= 375
    sol, rep = driver.solve(batch, segment_budget=budget)
    assert rep.compiles == 0  # budget is a runtime operand, no fork
    status = np.asarray(sol.status)
    iters = np.asarray(sol.iters)
    assert status[STRAGGLER] == Status.MAX_ITER
    assert iters[STRAGGLER] == budget * PARAMS.check_interval
    assert rep.max_iter_lanes == 1

    # Budget semantics == the fused solve with max_iter = budget * ci.
    import dataclasses

    capped = dataclasses.replace(
        PARAMS, max_iter=budget * PARAMS.check_interval)
    ref = solve_qp_batch(batch, capped)
    np.testing.assert_array_equal(np.asarray(ref.status), status)
    for i in range(B):
        np.testing.assert_array_equal(np.asarray(ref.x)[i],
                                      np.asarray(sol.x)[i],
                                      err_msg=f"lane {i}")


def test_budget_retirement_gets_polish_fallback(batch):
    """A lane retired out of budget still gets the active-set polish —
    and is re-graded SOLVED when the polished point meets tolerance
    (the 'MAX_ITER + polish fallback' path)."""
    import dataclasses

    loose = dataclasses.replace(PARAMS, eps_abs=1e-5, eps_rel=1e-5,
                                polish=True)
    d = CompactingDriver(loose, segment_budget=2)
    sol, rep = d.solve(batch)
    status = np.asarray(sol.status)
    x = np.asarray(sol.x)
    assert np.all(np.isfinite(x))
    # Every lane was cut off at 50 iterations; the polish rescues the
    # well-conditioned ones to SOLVED, and whatever stays MAX_ITER
    # still carries a finite polished iterate + residuals.
    assert np.all((status == Status.SOLVED) | (status == Status.MAX_ITER))
    assert int(np.sum(status == Status.SOLVED)) >= B - 1
    assert np.all(np.asarray(sol.iters) <= 2 * PARAMS.check_interval)


def test_solve_batch_compacted_wrapper(batch, driver):
    from porqua_tpu.batch import BatchProblems, solve_batch_compacted

    problems = BatchProblems(
        qp=batch, rebdates=[str(i) for i in range(B)],
        universes=[[f"a{j}" for j in range(N)]] * B, n_assets_max=N)
    sol, rep = solve_batch_compacted(problems, PARAMS, driver=driver)
    assert rep.batch == B
    assert int(np.sum(np.asarray(sol.status) == Status.SOLVED)) == B - 1


# ---------------------------------------------------------------------------
# contracts + sanitizer
# ---------------------------------------------------------------------------

def test_repack_jaxpr_contracts():
    """The step+repack program (and the continuous triple) is free of
    host callbacks/transfers and dtype leaks — GC101-103 traced on the
    exact code the driver compiles."""
    from porqua_tpu.analysis.contracts import (
        check_closed_jaxpr, compaction_step_jaxpr, continuous_jaxprs)

    findings = check_closed_jaxpr(
        compaction_step_jaxpr(batch=4, group=2, n=8, m=2),
        "compaction_step")
    for label, jaxpr in continuous_jaxprs(batch=2, n=8, m=2):
        findings += check_closed_jaxpr(jaxpr, label)
    assert findings == []


def test_repack_sanitized_no_implicit_transfers(batch, driver,
                                                monkeypatch):
    """PORQUA_SANITIZE=1: the whole compacted solve loop runs inside
    jax.transfer_guard('disallow') — the repack path performs no
    implicit h2d/d2h transfers (control readouts are explicit
    device_get) — and a prewarmed solve demands no compiles."""
    from porqua_tpu.analysis import sanitize

    dev_batch = jax.device_put(batch)
    monkeypatch.setenv("PORQUA_SANITIZE", "1")
    assert sanitize.enabled()
    sol, rep = driver.solve(dev_batch, segment_budget=4)
    assert rep.compiles == 0
    assert np.all(np.isfinite(np.asarray(sol.x)))


# ---------------------------------------------------------------------------
# continuous batching in serve
# ---------------------------------------------------------------------------

SERVE_PARAMS = SolverParams(max_iter=500, eps_abs=1e-5, eps_rel=1e-5,
                            polish=False, check_interval=25)
SERVE_BUDGET = 6  # 150 iterations: plenty for the clean lanes, far
#                   short of the ill-conditioned lane's requirement


def _serve_qp(n=6, m=2, seed=0, ill=False):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * n, n))
    P = _ill_P(rng, n) if ill else A.T @ A / (2 * n) + np.eye(n)
    q = rng.standard_normal(n)
    C = np.concatenate([np.ones((1, n)),
                        rng.standard_normal((m - 1, n))])
    return CanonicalQP.build(P, q, C=C, l=np.full(m, -1.0), u=np.ones(m),
                             lb=np.zeros(n), ub=np.ones(n))


@pytest.fixture(scope="module")
def service():
    """One started+prewarmed continuous service shared by the serve
    tests (the per-lane budget is batcher state, so tests that need
    retirement use the ill-conditioned problem against SERVE_BUDGET).
    Each test calls ``metrics.reset_window()`` for its own counters."""
    from porqua_tpu.serve import BucketLadder, SolveService

    svc = SolveService(params=SERVE_PARAMS,
                       ladder=BucketLadder(n_rungs=(8, 16),
                                           m_rungs=(4, 8)),
                       max_batch=4, max_wait_ms=2.0,
                       continuous=True, segment_budget=SERVE_BUDGET)
    svc.start()
    svc.prewarm(_serve_qp())
    yield svc
    svc.stop()


def test_continuous_stream_solves_and_refills(service):
    """More requests than cohort slots: freed slots refill from the
    queue (continuous batching), every request resolves with its
    per-lane Status, and the segment counters populate."""
    from porqua_tpu.qp.solve import solve_qp

    service.metrics.reset_window()
    tickets = [service.submit(_serve_qp(seed=i), warm_key=str(i))
               for i in range(10)]
    results = [service.result(t, timeout=120) for t in tickets]
    assert all(r.found for r in results)
    assert all(r.status == Status.SOLVED for r in results)
    snap = service.snapshot()
    assert snap["lanes_admitted"] == 10
    assert snap["completed"] == 10
    assert snap["status_solved"] == 10
    assert snap["lane_segments"] > 0
    assert 0.0 < snap["segment_occupancy_mean"] <= 1.0
    assert snap["compiles"] == 0  # prewarm covered the whole ladder
    # Result parity with the one-shot solver (same params, same
    # bucket-padded problem class).
    ref = solve_qp(_serve_qp(seed=3), SERVE_PARAMS)
    np.testing.assert_allclose(results[3].x, np.asarray(ref.x)[:6],
                               atol=1e-5)


def test_continuous_segment_budget_retires_max_iter(service):
    """An ill-conditioned request at the cohort's segment budget
    retires as MAX_ITER (polish off, so nothing rescues it) while a
    clean cohort mate still solves — the straggler stops taxing cohort
    latency and is distinguishable at the API boundary."""
    service.metrics.reset_window()
    t_bad = service.submit(_serve_qp(seed=1, ill=True))
    t_ok = service.submit(_serve_qp(seed=2))
    bad = service.result(t_bad, timeout=120)
    ok = service.result(t_ok, timeout=120)
    assert bad.status == Status.MAX_ITER and not bad.found
    assert bad.iters == SERVE_BUDGET * SERVE_PARAMS.check_interval
    assert ok.status == Status.SOLVED
    snap = service.snapshot()
    assert snap["lanes_retired_budget"] >= 1
    assert snap["status_max_iter"] >= 1
    assert snap["status_solved"] >= 1


def test_continuous_warm_start_cache_round_trip(service):
    """A repeat rebalance under the same warm_key warm-starts in the
    continuous path too."""
    first = service.result(
        service.submit(_serve_qp(seed=5), warm_key="book"), timeout=120)
    second = service.result(
        service.submit(_serve_qp(seed=5), warm_key="book"), timeout=120)
    assert not first.warm_started
    assert second.warm_started
    assert second.iters <= first.iters


def test_continuous_budget_clamped_to_max_iter_semantics():
    """A requested budget wider than ceil(max_iter/check_interval) is
    clamped: the continuous step program has no max_iter brake of its
    own, so the clamp is what keeps serve retirement identical to the
    compaction driver's lane_active policy."""
    from porqua_tpu.qp.solve import default_segment_budget
    from porqua_tpu.serve import BucketLadder, SolveService

    svc = SolveService(params=SERVE_PARAMS,
                       ladder=BucketLadder(n_rungs=(8,), m_rungs=(4,)),
                       max_batch=4, continuous=True, segment_budget=999)
    assert svc.batcher.segment_budget == default_segment_budget(
        SERVE_PARAMS)  # = 500/25 = 20, not 999


def test_continuous_cohort_replaced_when_queue_outgrows_it(service):
    """A cohort minted from the first trickle of a ramping stream must
    not permanently cap the bucket's throughput: when the queue
    outgrows it, it stops refilling, drains, and a larger replacement
    is sized from the backlog. (White-box: drives the batcher's tick
    directly so the policy is deterministic — the live thread in the
    shared service is quiesced by using a separate, unstarted one.)"""
    import collections
    import time
    from concurrent.futures import Future

    from porqua_tpu.serve import BucketLadder, SolveService
    from porqua_tpu.serve.batcher import SolveRequest

    svc = SolveService(params=SERVE_PARAMS,
                       ladder=BucketLadder(n_rungs=(8, 16),
                                           m_rungs=(4, 8)),
                       max_batch=8, continuous=True)
    # Executables come from the shared module service's prewarmed
    # ladder? No — caches are per service; prewarm this one (slots 2
    # and 8 are both ladder rungs).
    svc.prewarm(_serve_qp())
    b = svc.batcher

    def req(seed):
        qp0 = _serve_qp(seed=seed)
        bk, pd = svc.ladder.pad(qp0)
        return bk, SolveRequest(qp=pd, bucket=bk, n_orig=qp0.n,
                                m_orig=qp0.m, future=Future(),
                                submitted=time.monotonic())

    bucket, r0 = req(0)
    _, r1 = req(1)
    dq = collections.deque([r0, r1])
    b._pending[bucket] = dq
    b._make_cohort_safe(bucket, dq)
    cohort = b._cohorts[bucket]
    assert cohort.slots == 2
    b._tick(bucket, cohort)  # admits + first segment for the two
    assert not cohort.no_refill

    dq.extend(req(i)[1] for i in range(2, 14))
    for _ in range(60):
        b._tick(bucket, cohort)
        if cohort.empty():
            break
    assert cohort.no_refill  # the backlog outgrew the cohort
    assert cohort.empty()    # in-flight lanes finished normally
    assert r0.future.done() and r1.future.done()
    assert r0.future.result().found
    assert len(dq) == 12     # backlog untouched by the draining cohort
    assert svc.metrics.counters["cohort_replacements"] >= 1

    # The replacement is sized from the backlog, not the old cohort.
    del b._cohorts[bucket]
    b._make_cohort_safe(bucket, dq)
    assert b._cohorts[bucket].slots == 8
    for r in dq:
        r.future.cancel()


def test_loadgen_continuous_reports_status_counts():
    """The loadgen report surfaces per-lane Status counts and the
    segment-occupancy metrics for a continuous run."""
    from porqua_tpu.serve.loadgen import build_tracking_requests, run_loadgen

    requests = build_tracking_requests(6, n_assets=8, window=16)
    report = run_loadgen(requests, params=SERVE_PARAMS, max_batch=2,
                         continuous=True)
    assert report["continuous"] is True
    assert report["recompiles_after_warmup"] == 0
    assert sum(report["status_counts"].values()) == 6
    assert report["status_counts"].get("solved", 0) == 6
    assert report["lane_segments"] > 0
    assert 0.0 <= report["wasted_lane_fraction"] < 1.0
