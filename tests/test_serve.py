"""Online solve service (porqua_tpu.serve): bucketing, the compiled-
executable cache, micro-batch coalescing, deadlines, warm starts, and
the TPU -> XLA-CPU degradation path — all on the CPU backend (the
serve stack is device-agnostic; only the DeviceHealth pair changes on
hardware).
"""

import time

import numpy as np
import pytest

from porqua_tpu.qp.canonical import CanonicalQP, pad_qp
from porqua_tpu.qp.solve import SolverParams, solve_qp
from porqua_tpu.serve import (
    Bucket,
    BucketLadder,
    BucketOverflow,
    DeadlineExpired,
    DeviceHealth,
    ExecutableCache,
    ServeMetrics,
    SolveService,
    slot_count,
    slot_ladder,
)

# One loose-but-converged config shared by every service test: small
# compiles, and distinct SolverParams would needlessly fork executable
# caches.
PARAMS = SolverParams(max_iter=500, eps_abs=1e-5, eps_rel=1e-5,
                      polish=False, check_interval=25)
LADDER = BucketLadder(n_rungs=(8, 16), m_rungs=(4, 8))


def make_qp(n=6, m=2, seed=0, dtype=None):
    """A well-conditioned random inequality QP at its natural shape."""
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * n, n))
    P = A.T @ A / (2 * n) + np.eye(n)
    q = rng.standard_normal(n)
    C = np.concatenate([np.ones((1, n)), rng.standard_normal((m - 1, n))])
    return CanonicalQP.build(
        P, q, C=C, l=np.full(m, -1.0), u=np.ones(m),
        lb=np.zeros(n), ub=np.ones(n), dtype=dtype)


def service(**kw):
    kw.setdefault("params", PARAMS)
    kw.setdefault("ladder", LADDER)
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5.0)
    return SolveService(**kw)


# ---------------------------------------------------------------------------
# bucketing
# ---------------------------------------------------------------------------

def test_bucket_selection():
    ladder = BucketLadder(n_rungs=(8, 16, 32), m_rungs=(4, 16))
    assert ladder.select(make_qp(6, 2)) == Bucket(8, 4, None)
    assert ladder.select(make_qp(8, 4)) == Bucket(8, 4, None)
    assert ladder.select(make_qp(9, 5)) == Bucket(16, 16, None)
    with pytest.raises(BucketOverflow):
        ladder.select(make_qp(33, 2))
    # The factor's row count is part of the bucket identity (it is a
    # capacitance dimension, never padded).
    X = np.random.default_rng(1).standard_normal((5, 6))
    qp_f = CanonicalQP.build(2 * X.T @ X, np.zeros(6), Pf=X)
    assert ladder.select(qp_f) == Bucket(8, 4, 5)
    # A factored problem must carry a Pdiag leaf after padding even on
    # the exact-fit path: Pdiag=None would change the pytree structure
    # vs the AOT executable's and break stack_qps for mixed batches.
    X8 = np.random.default_rng(2).standard_normal((5, 8)).astype(np.float32)
    qp_fit = CanonicalQP(
        P=2 * X8.T @ X8, q=np.zeros(8, np.float32),
        C=np.zeros((4, 8), np.float32), l=np.full(4, -1.0, np.float32),
        u=np.ones(4, np.float32), lb=np.zeros(8, np.float32),
        ub=np.ones(8, np.float32), var_mask=np.ones(8, np.float32),
        row_mask=np.ones(4, np.float32), constant=np.float32(0.0),
        Pf=X8)  # Pdiag defaults to None
    assert qp_fit.Pdiag is None and (qp_fit.n, qp_fit.m) == (8, 4)
    _, padded_fit = ladder.pad(qp_fit)
    assert padded_fit.Pdiag is not None
    _, padded_up = ladder.pad(qp_f)
    from porqua_tpu.qp.canonical import stack_qps
    stacked = stack_qps([padded_fit, padded_fit], stack_fn=np.stack)
    assert stacked.Pdiag.shape == (2, 8)


def test_slot_ladder():
    assert [slot_count(k, 8) for k in (1, 2, 3, 5, 8, 11)] == [1, 2, 4, 8, 8, 8]
    assert slot_ladder(8) == (1, 2, 4, 8)
    assert slot_ladder(6) == (1, 2, 4, 6)
    with pytest.raises(ValueError):
        slot_count(0, 8)


def test_padding_round_trip():
    """A bucket-padded problem solves to the same solution, with exact
    zeros in the padding slots (the canonical neutrality scheme)."""
    qp = make_qp(6, 2, seed=3, dtype=np.float64)
    bucket, padded = BucketLadder((8, 16), (4, 8)).pad(qp)
    assert bucket == Bucket(8, 4, None)
    assert padded.P.shape == (8, 8) and padded.C.shape == (4, 8)
    assert isinstance(padded.q, np.ndarray)
    np.testing.assert_array_equal(padded.var_mask, [1] * 6 + [0] * 2)
    np.testing.assert_array_equal(padded.row_mask, [1, 1, 0, 0])

    params = SolverParams(polish=False)
    ref = solve_qp(qp, params)
    got = solve_qp(CanonicalQP(*(None if a is None else np.asarray(a)
                                 for a in padded)), params)
    assert int(got.status) == 1
    np.testing.assert_allclose(np.asarray(got.x)[:6], np.asarray(ref.x),
                               atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got.x)[6:], 0.0)


def test_executable_cache_hit_miss_accounting():
    metrics = ServeMetrics()
    cache = ExecutableCache(PARAMS, metrics=metrics)
    qp = make_qp(6, 2)
    bucket, padded = LADDER.pad(qp)
    dt = padded.q.dtype

    e1 = cache.get(bucket, 2, dt)
    assert metrics.counters["compiles"] == 1
    assert cache.get(bucket, 2, dt) is e1
    assert metrics.counters["cache_hits"] == 1
    # A different slot count is a different executable...
    cache.get(bucket, 4, dt)
    assert metrics.counters["compiles"] == 2
    # ...and prewarm fills exactly the missing rungs of the ladder.
    compiled = cache.prewarm(bucket, 4, dt)
    assert compiled == 1  # slots 1 (2 and 4 already exist)
    assert len(cache) == 3
    assert cache.prewarm(bucket, 4, dt) == 0
    assert metrics.counters["compiles"] == 3


# ---------------------------------------------------------------------------
# batching / service
# ---------------------------------------------------------------------------

def test_batcher_coalesces_and_matches_direct_solve():
    qps = [make_qp(6, 2, seed=s) for s in range(12)]
    refs = [np.asarray(solve_qp(q, PARAMS).x) for q in qps]
    with service(max_batch=8, max_wait_ms=25.0) as svc:
        tickets = [svc.submit(q) for q in qps]
        results = [svc.result(t, timeout=120) for t in tickets]
    assert all(r.found for r in results)
    for r, ref, qp in zip(results, refs, qps):
        assert r.x.shape == (qp.n,)
        np.testing.assert_allclose(r.x, ref, atol=5e-4)
    snap = svc.snapshot()
    # 12 requests must have ridden far fewer dispatches (a full batch
    # of 8 + the 4-slot remainder under the age trigger, typically).
    assert snap["batches"] < 12
    assert snap["completed"] == 12
    assert snap["batch_occupied"] == 12
    assert snap["occupancy_mean"] >= 0.5
    assert snap["failed"] == 0 and snap["expired"] == 0


def test_deadline_expiry():
    with service(max_wait_ms=150.0) as svc:
        # The age trigger fires at 150 ms; a 1 ms deadline must expire
        # before dispatch, without poisoning the later request.
        doomed = svc.submit(make_qp(seed=1), deadline_s=0.001)
        time.sleep(0.02)
        ok = svc.submit(make_qp(seed=2))
        with pytest.raises(DeadlineExpired):
            svc.result(doomed, timeout=120)
        assert svc.result(ok, timeout=120).found
    snap = svc.snapshot()
    assert snap["expired"] == 1
    assert snap["completed"] == 1


def test_warm_start_cache():
    qp = make_qp(6, 2, seed=7)
    with service() as svc:
        first = svc.solve(qp, timeout=120, warm_key="fund-a")
        second = svc.solve(qp, timeout=120, warm_key="fund-a")
        other = svc.solve(qp, timeout=120, warm_key="fund-b")
    assert not first.warm_started
    assert second.warm_started
    assert not other.warm_started
    assert svc.snapshot()["warm_hits"] == 1
    # Warm-started from its own solution, the repeat solve stays there.
    np.testing.assert_allclose(second.x, first.x, atol=5e-4)


def test_fingerprint_warm_keys():
    """With fingerprint_warm_keys, a repeat rebalance (same feasible
    set, different objective) warm-starts without any explicit key; a
    different polytope does not."""
    from porqua_tpu.serve import problem_fingerprint

    day1 = make_qp(6, 2, seed=11)
    day2 = day1._replace(q=np.asarray(day1.q) + 0.01)  # same polytope
    other = make_qp(6, 3, seed=11)                     # different rows
    assert problem_fingerprint(day1) == problem_fingerprint(day2)
    assert problem_fingerprint(day1) != problem_fingerprint(other)
    with service(fingerprint_warm_keys=True) as svc:
        assert not svc.solve(day1, timeout=120).warm_started
        assert svc.solve(day2, timeout=120).warm_started
        assert not svc.solve(other, timeout=120).warm_started


def test_degrades_to_cpu_on_probe_failure():
    """The VERDICT.md failure mode: the primary device black-holes.
    Forced probe failure must trip the breaker at startup and the whole
    request stream must complete on the XLA-CPU fallback — degraded,
    not erroring."""
    import jax

    devices = jax.devices()
    primary = devices[-1]        # stands in for the TPU
    fallback = jax.devices("cpu")[0]
    assert primary is not fallback  # conftest forces 8 virtual devices

    metrics = ServeMetrics()
    health = DeviceHealth(
        primary=primary, fallback=fallback,
        probe_fn=lambda dev: (_ for _ in ()).throw(RuntimeError("dead")),
        failure_threshold=2, probe_timeout_s=2.0,
        recovery_interval_s=3600.0, metrics=metrics)
    with service(metrics=metrics, health=health) as svc:
        assert svc.health.degraded
        tickets = [svc.submit(make_qp(seed=s)) for s in range(5)]
        results = [svc.result(t, timeout=120) for t in tickets]
    assert all(r.found for r in results)
    assert all(r.device == "cpu:0" for r in results)
    snap = svc.snapshot()
    assert snap["degraded"] is True
    assert snap["device"] == "cpu:0"
    assert snap["probe_failures"] >= 2
    assert snap["device_switches"] == 1
    assert snap["failed"] == 0


def test_metrics_snapshot_jsonl_and_tracer_bridge(tmp_path):
    from porqua_tpu.profiling import Tracer

    with service() as svc:
        svc.solve(make_qp(seed=9), timeout=120)
        path = tmp_path / "serve.jsonl"
        snap = svc.metrics.write_jsonl(str(path))
    for key in ("latency_p50_ms", "latency_p99_ms", "occupancy_mean",
                "throughput_solves_per_s", "compiles", "queue_depth_max"):
        assert key in snap
    import json

    lines = path.read_text().strip().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["completed"] == 1

    tracer = Tracer()
    svc.metrics.bridge_tracer(tracer)
    stages = {t.name for t in tracer.timings}
    assert {"serve/solve", "serve/compile"} <= stages


def test_as_requests_bridge_round_trip():
    """batch.as_requests unstacks a stacked batch into per-date
    requests the service solves to the batch engine's answers."""
    from porqua_tpu.batch import BatchProblems, as_requests
    from porqua_tpu.qp.canonical import stack_qps
    from porqua_tpu.qp.solve import solve_qp_batch

    qps = [make_qp(6, 2, seed=s) for s in (20, 21, 22)]
    problems = BatchProblems(
        qp=stack_qps(qps), rebdates=["d0", "d1", "d2"],
        universes=[[f"a{i}" for i in range(6)]] * 3, n_assets_max=6)
    singles = as_requests(problems)
    assert len(singles) == 3 and singles[0].P.shape == (6, 6)
    batch_sol = solve_qp_batch(problems.qp, PARAMS)
    with service() as svc:
        results = [svc.solve(q, timeout=120) for q in singles]
    for i, r in enumerate(results):
        np.testing.assert_allclose(
            r.x, np.asarray(batch_sol.x)[i, :6], atol=5e-4)


def test_metrics_concurrent_hammer():
    """The docstring claims every mutator takes the lock; exercise it:
    hammer inc/observe_latency/observe_queue_wait/snapshot from threads
    and assert exact counter totals and percentile sanity. Also pins
    the reservoir-overwrite fix: the overwrite index follows the
    reservoir's own observation counter, so a full reservoir keeps
    rotating instead of clobbering one slot."""
    import threading

    metrics = ServeMetrics(latency_reservoir=64)
    n_threads, n_iter = 8, 500
    errors = []

    def worker(k):
        try:
            for i in range(n_iter):
                metrics.inc("submitted")
                metrics.inc("completed", 2)
                metrics.observe_latency(0.001 * (k + 1))
                metrics.observe_queue_wait(0.002)
                if i % 50 == 0:
                    snap = metrics.snapshot()
                    assert snap["submitted"] >= 0
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    snap = metrics.snapshot()
    total = n_threads * n_iter
    assert snap["submitted"] == total
    assert snap["completed"] == 2 * total
    assert snap["queue_wait_seconds"] == pytest.approx(0.002 * total)
    # Percentiles come from the bounded reservoir: every sample is one
    # of the 8 per-thread values, and p50/p99 sit inside their range.
    lo, hi = 0.001e3, 0.008e3  # ms
    assert lo <= snap["latency_p50_ms"] <= hi
    assert lo <= snap["latency_p99_ms"] <= hi
    assert len(metrics._latencies) == 64


def test_latency_reservoir_rotates_without_completed():
    """Regression (reservoir overwrite bias): observe_latency used the
    `completed` counter — incremented on a different code path — as its
    overwrite index, so with completed frozen every overwrite hit slot
    0. The reservoir now rotates on its own observation count."""
    metrics = ServeMetrics(latency_reservoir=4)
    for v in (1.0, 2.0, 3.0, 4.0):   # fill
        metrics.observe_latency(v)
    # completed stays 0 the whole time; overwrites must still rotate.
    for v in (5.0, 6.0):
        metrics.observe_latency(v)
    assert sorted(metrics._latencies) == [3.0, 4.0, 5.0, 6.0]
    assert metrics.counters["completed"] == 0


def test_queue_backpressure_counts_rejections():
    from porqua_tpu.serve import QueueFull

    svc = service(queue_capacity=1)
    # Not started: the batcher never drains, so the second submit must
    # hit the bounded queue. Start/stop around it to satisfy the
    # lifecycle guard without a live consumer.
    svc._started = True
    svc.submit(make_qp(seed=30))
    with pytest.raises(QueueFull):
        svc.submit(make_qp(seed=31), timeout=0.05)
    assert svc.snapshot()["rejected"] == 1
    assert svc.snapshot()["submitted"] == 1
