"""Factored-objective (Woodbury) linear-solve path.

The north-star tracking QP has P = 2 X'X with window T < universe n, so
the solver can run every factorization on the (T+m)-dim capacitance
matrix instead of the n x n KKT (``linsolve="woodbury"``,
``qp/admm.py:factored_spd_solve_operator``) and the polish can pin
actives exactly in the factored frame
(``qp/polish.py:_kkt_solve_factored``). These tests pin that path to
the dense-Cholesky path bit-for-bit-defined behavior on CPU in both
dtypes; real-hardware behavior is covered by ``test_tpu_hardware.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from porqua_tpu.qp.admm import SolverParams, factored_spd_solve_operator
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.ruiz import equilibrate
from porqua_tpu.qp.solve import solve_qp, solve_qp_batch
from porqua_tpu.tracking import build_tracking_qp, synthetic_universe


def _params(ls, dtype, **kw):
    eps = 1e-10 if dtype == jnp.float64 else 1e-3
    kw.setdefault("eps_abs", eps)
    kw.setdefault("eps_rel", eps)
    return SolverParams(max_iter=4000, linsolve=ls, **kw)


def test_operator_matches_dense_solve():
    key = jax.random.PRNGKey(0)
    n, k = 37, 11
    V = jax.random.normal(key, (k, n), dtype=jnp.float64)
    Dv = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (n,),
                                   dtype=jnp.float64)) + 0.1
    K = jnp.diag(Dv) + V.T @ V
    rhs = jax.random.normal(jax.random.PRNGKey(2), (n,), dtype=jnp.float64)
    x = factored_spd_solve_operator(Dv, V)(rhs)
    np.testing.assert_allclose(np.asarray(K @ x), np.asarray(rhs),
                               rtol=0, atol=1e-11)


def test_operator_pins_zeroed_columns_exactly():
    # Columns of V that are zero (pinned/padded variables) must be
    # reproduced as rhs / D exactly — the polish relies on this.
    n, k = 16, 5
    V = jax.random.normal(jax.random.PRNGKey(0), (k, n), dtype=jnp.float64)
    mask = (jnp.arange(n) % 3 != 0)
    V = V * mask[None, :]
    Dv = jnp.full((n,), 2.0, dtype=jnp.float64)
    rhs = jax.random.normal(jax.random.PRNGKey(1), (n,), dtype=jnp.float64)
    x = factored_spd_solve_operator(Dv, V, refine_steps=0)(rhs)
    np.testing.assert_array_equal(
        np.asarray(x)[~np.asarray(mask)],
        np.asarray(rhs / 2.0)[~np.asarray(mask)])


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.float32])
def test_tracking_solution_matches_chol_path(dtype):
    Xs, ys = synthetic_universe(jax.random.PRNGKey(3), n_dates=4, window=60,
                                n_assets=40, dtype=dtype)
    qp = jax.vmap(build_tracking_qp)(Xs, ys)
    sw = solve_qp_batch(qp, _params("woodbury", dtype))
    sc = solve_qp_batch(qp, _params("chol", dtype))
    assert np.all(np.asarray(sw.status) == 1)
    # f32 runs at eps 1e-3: the two paths exit ADMM at slightly
    # different iterates, so the polished active sets can differ on
    # near-degenerate coordinates — compare weights at the iterate
    # grade and objectives tightly instead.
    atol = 1e-7 if dtype == jnp.float64 else 3e-3
    np.testing.assert_allclose(np.asarray(sw.x), np.asarray(sc.x),
                               rtol=0, atol=atol)
    np.testing.assert_allclose(np.asarray(sw.obj_val),
                               np.asarray(sc.obj_val),
                               rtol=1e-7 if dtype == jnp.float64 else 1e-3)
    # The polish must reach the same residual grade as the dense path.
    assert float(jnp.max(sw.prim_res)) <= 10 * max(
        float(jnp.max(sc.prim_res)), np.finfo(np.asarray(sc.x).dtype).eps)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.float32])
def test_scaling_preserves_factor_identity(dtype):
    Xs, ys = synthetic_universe(jax.random.PRNGKey(4), n_dates=1, window=50,
                                n_assets=30, dtype=dtype)
    qp = build_tracking_qp(Xs[0], ys[0], ridge=1e-3)
    scaled, _ = equilibrate(qp)
    P_rebuilt = 2.0 * scaled.Pf.T @ scaled.Pf + jnp.diag(scaled.Pdiag)
    tol = 1e-12 if dtype == jnp.float64 else 1e-5
    np.testing.assert_allclose(np.asarray(P_rebuilt), np.asarray(scaled.P),
                               rtol=0, atol=tol)


def test_woodbury_requires_factor():
    n = 8
    qp = CanonicalQP.build(np.eye(n), np.zeros(n), lb=np.zeros(n),
                           ub=np.ones(n))
    with pytest.raises(ValueError, match="requires the factored"):
        solve_qp(qp, SolverParams(linsolve="woodbury"))


def test_l1_turnover_matches_chol_path():
    dtype = jnp.float64
    Xs, ys = synthetic_universe(jax.random.PRNGKey(5), n_dates=3, window=60,
                                n_assets=40, dtype=dtype)
    qp = jax.vmap(build_tracking_qp)(Xs, ys)
    l1w = jnp.full((3, 40), 5e-4, dtype)
    l1c = jnp.full((3, 40), 1.0 / 40, dtype)
    sw = solve_qp_batch(qp, _params("woodbury", dtype),
                        l1_weight=l1w, l1_center=l1c)
    sc = solve_qp_batch(qp, _params("chol", dtype),
                        l1_weight=l1w, l1_center=l1c)
    assert np.all(np.asarray(sw.status) == 1)
    np.testing.assert_allclose(np.asarray(sw.x), np.asarray(sc.x),
                               rtol=0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(sw.obj_val), np.asarray(sc.obj_val),
                               rtol=1e-7, atol=0)


def test_ridge_diag_flows_through():
    dtype = jnp.float64
    Xs, ys = synthetic_universe(jax.random.PRNGKey(6), n_dates=1, window=60,
                                n_assets=40, dtype=dtype)
    qp = build_tracking_qp(Xs[0], ys[0], ridge=1e-2)
    sw = solve_qp(qp, _params("woodbury", dtype))
    sc = solve_qp(qp, _params("chol", dtype))
    assert int(sw.status) == 1
    np.testing.assert_allclose(np.asarray(sw.x), np.asarray(sc.x),
                               rtol=0, atol=1e-8)


def test_all_variables_pinned_degenerate_schur():
    # ub = 1/n forces every variable to its bound, so the polish's
    # active set pins ALL coordinates: C Z == 0 makes the budget row's
    # Schur diagonal exactly zero. The dead-row guard must drop the row
    # (not emit inf/NaN) and the solve must still land on the vertex.
    dtype = jnp.float64
    n = 16
    Xs, ys = synthetic_universe(jax.random.PRNGKey(8), n_dates=1, window=30,
                                n_assets=n, dtype=dtype)
    qp = build_tracking_qp(Xs[0], ys[0], ub=1.0 / n)
    sol = solve_qp(qp, _params("woodbury", dtype))
    assert int(sol.status) == 1
    assert bool(jnp.all(jnp.isfinite(sol.x)))
    np.testing.assert_allclose(np.asarray(sol.x), np.full(n, 1.0 / n),
                               rtol=0, atol=1e-9)


def test_mesh_padding_keeps_factor_structure():
    from porqua_tpu.parallel.mesh import pad_batch_to_mesh

    Xs, ys = synthetic_universe(jax.random.PRNGKey(7), n_dates=3, window=20,
                                n_assets=12, dtype=jnp.float64)
    qp = jax.vmap(build_tracking_qp)(Xs, ys)
    padded, n_real = pad_batch_to_mesh(qp, 4)
    assert n_real == 3 and padded.P.shape[0] == 4
    assert padded.Pf.shape == (4, 20, 12)
    # Filler problems keep P == 2 Pf'Pf + diag(Pdiag) (identity).
    np.testing.assert_allclose(
        np.asarray(2.0 * padded.Pf[-1].T @ padded.Pf[-1]
                   + jnp.diag(padded.Pdiag[-1])),
        np.asarray(padded.P[-1]), rtol=0, atol=0)
    sol = solve_qp_batch(padded, _params("woodbury", jnp.float64))
    assert np.all(np.asarray(sol.status) == 1)


def test_polish_iteration_recovers_from_rejected_first_pass():
    """Regression pin for the round-3 active-set-iteration fix: from a
    loose (eps 1e-3) f32 iterate on the north-star tracking problem the
    FIRST polish candidate is rejected (borderline unpinned variables
    dip out of bounds, raising the primal residual), and the old
    pass loop fix-pointed on that rejection. Threading the candidate
    forward must land near-exact constraint satisfaction by pass 2."""
    import jax

    from porqua_tpu.qp.admm import admm_solve, _residuals
    from porqua_tpu.qp.polish import polish_iterate
    from porqua_tpu.qp.ruiz import equilibrate
    from porqua_tpu.tracking import build_tracking_qp, synthetic_universe_np

    Xs, ys = synthetic_universe_np(seed=42, n_dates=1, window=252,
                                   n_assets=500)
    qp = build_tracking_qp(jnp.asarray(Xs[0], jnp.float32),
                           jnp.asarray(ys[0], jnp.float32))
    params = SolverParams(max_iter=2000, eps_abs=1e-3, eps_rel=1e-3,
                          scaling_iters=2)
    scaled, scaling = equilibrate(qp, iters=2)
    st = admm_solve(scaled, scaling, params)
    it5 = (st.x, st.z, st.w, st.y, st.mu)

    # One pass alone is rejected on this iterate (the setup the fix
    # addresses): the point comes back unchanged.
    one = polish_iterate(scaled, scaling, params, *it5, passes=1)
    assert bool(jnp.all(one[0] == st.x)), "expected first pass rejected"

    # Two threaded passes recover: budget exact to f32 roundoff.
    two = polish_iterate(scaled, scaling, params, *it5, passes=2)
    x_u = scaling.D * two[0]
    assert abs(float(jnp.sum(x_u)) - 1.0) < 1e-5
    rp, rd, *_ = _residuals(scaled, scaling, *two, params)
    assert float(rp) < 1e-5


class TestFactoredScaling:
    """scaling_mode="factored" (round 4): Jacobi scaling from the
    objective factor, no dense-P Ruiz sweeps — the TPU headline
    config's scaling stage. Quality parity with Ruiz on the tracking
    workload is the promotion contract."""

    def test_factored_scaling_matches_ruiz_solution(self, rng):
        import dataclasses

        from porqua_tpu.tracking import build_tracking_qp

        X = jnp.asarray(rng.standard_normal((96, 40)) * 0.01, jnp.float64)
        y = jnp.asarray(np.asarray(X) @ (np.ones(40) / 40), jnp.float64)
        qp = build_tracking_qp(X, y)
        base = SolverParams(max_iter=4000, eps_abs=1e-9, eps_rel=1e-9,
                            linsolve="woodbury", woodbury_refine=0)
        fac = dataclasses.replace(base, scaling_mode="factored")
        ref = solve_qp(qp, base)
        got = solve_qp(qp, fac)
        assert bool(got.found) and bool(ref.found)
        np.testing.assert_allclose(np.asarray(got.x), np.asarray(ref.x),
                                   atol=1e-7)

    def test_factored_scaling_equilibrates_uniformly_tiny_problems(self, rng):
        """Round-5 advisor fix: the live/padded cut is the exact-zero
        test, not a magnitude floor — a uniformly tiny-scaled factor
        (every P_jj far below any absolute threshold) must still
        equilibrate to a unit-diagonal scaled P."""
        from porqua_tpu.qp.canonical import CanonicalQP
        from porqua_tpu.qp.ruiz import equilibrate_factored

        n = 12
        F = jnp.asarray(rng.standard_normal((20, n)) * 1e-8, jnp.float64)
        P = 2.0 * F.T @ F
        qp = CanonicalQP.build(np.asarray(P), np.zeros(n),
                               C=np.ones((1, n)), l=np.ones(1),
                               u=np.ones(1), lb=np.zeros(n),
                               ub=np.ones(n), Pf=np.asarray(F),
                               dtype=jnp.float64)
        scaled, scaling = equilibrate_factored(qp)
        diag = np.diag(np.asarray(scaled.P)) / float(scaling.c)
        np.testing.assert_allclose(diag, 1.0, rtol=1e-6)
        assert float(jnp.max(scaling.D)) > 1e3  # actually rescaled

    def test_factored_scaling_bench_shard_parity_f32(self, rng):
        """The exact bench headline config at a north-star shard on the
        suite's CPU backend: all solved, one clean segment, TE parity
        with Ruiz x2 (the measurement quoted in bench.py)."""
        import dataclasses

        from porqua_tpu.tracking import synthetic_universe_np, tracking_step_jit

        Xs_np, ys_np = synthetic_universe_np(seed=42, n_dates=8,
                                             window=252, n_assets=500)
        Xs, ys = jnp.asarray(Xs_np), jnp.asarray(ys_np)
        wb = SolverParams(max_iter=2000, eps_abs=1e-3, eps_rel=1e-3,
                          polish=False, scaling_iters=2,
                          linsolve="woodbury", woodbury_refine=0,
                          check_interval=35)
        fac = dataclasses.replace(wb, scaling_mode="factored")
        out_r = tracking_step_jit(Xs, ys, wb)
        out_f = tracking_step_jit(Xs, ys, fac)
        assert int(jnp.sum(out_f.status == 1)) == 8
        # One clean segment: no straggler lanes under factored scaling.
        assert int(jnp.max(out_f.iters)) == 35, np.asarray(out_f.iters)
        np.testing.assert_allclose(
            np.asarray(out_f.tracking_error),
            np.asarray(out_r.tracking_error), rtol=2e-3)

    def test_factored_scaling_requires_factor(self, rng):
        qp = CanonicalQP.build(
            P=np.eye(4), q=np.zeros(4), C=np.ones((1, 4)), l=np.ones(1),
            u=np.ones(1), lb=np.zeros(4), ub=np.ones(4),
            dtype=jnp.float64)
        with pytest.raises(ValueError, match="factored"):
            solve_qp(qp, SolverParams(scaling_mode="factored"))

    def test_dense_p_elided_from_compiled_headline_program(self):
        """Regression pin for the round-4 dense-P elision: the compiled
        north-star program under the full headline config (woodbury +
        factored scaling + polish off) must contain NO n x n dot —
        a new dense-P consumer anywhere in the pipeline would silently
        re-introduce the Gram build and ~1 GB of HBM traffic."""
        from porqua_tpu.tracking import synthetic_universe_np, tracking_step

        Xs_np, ys_np = synthetic_universe_np(seed=1, n_dates=2,
                                             window=96, n_assets=160)
        Xs, ys = jnp.asarray(Xs_np), jnp.asarray(ys_np)
        fac = SolverParams(max_iter=2000, eps_abs=1e-3, eps_rel=1e-3,
                           polish=False, linsolve="woodbury",
                           woodbury_refine=0, check_interval=35,
                           scaling_mode="factored")
        hlo = (jax.jit(lambda X: tracking_step(X, ys, fac))
               .lower(Xs).compile().as_text())
        n = 160
        bad = [ln for ln in hlo.splitlines()
               if "dot(" in ln and f"{n},{n}" in ln.replace(" ", "")]
        assert not bad, bad[:3]

    def test_headline_program_has_no_default_precision_dots(self):
        """Regression pin for the round-5 bf16-floor fix: every
        dot_general in the lowered headline program must carry
        Precision.HIGHEST. On TPU the DEFAULT precision computes f32
        matmuls in bf16 passes (~4e-3 relative), which floored the
        measurable dual residual at ~1e-3 on hardware
        (TPU_TESTS_r05.txt, test_lad_halpern_prox_on_hardware) — a
        single new default-precision matvec anywhere in the solve
        pipeline would silently reintroduce it."""
        import re

        from porqua_tpu.tracking import synthetic_universe_np, tracking_step

        Xs_np, ys_np = synthetic_universe_np(seed=1, n_dates=2,
                                             window=96, n_assets=160)
        Xs, ys = jnp.asarray(Xs_np), jnp.asarray(ys_np)
        fac = SolverParams(max_iter=2000, eps_abs=1e-3, eps_rel=1e-3,
                           polish=False, linsolve="woodbury",
                           woodbury_refine=0, check_interval=35,
                           scaling_mode="factored")
        low = (jax.jit(lambda X: tracking_step(X, ys, fac))
               .lower(Xs).as_text())
        dots = re.findall(r"stablehlo\.dot_general.*", low)
        assert dots, "lowering produced no dot_general ops?"
        bad = [d[:140] for d in dots if "HIGHEST" not in d]
        assert not bad, bad[:3]
