"""Optimization strategy classes: objective math + end-to-end solves.

Covers the strategy layer the reference exercises only interactively
(``src/_quick_and_dirty_interactive_testing.py``): QEQW, MeanVariance,
WeightedLeastSquares, LAD, PercentilePortfolios.
"""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from porqua_tpu import (
    LAD,
    LeastSquares,
    MeanVariance,
    PercentilePortfolios,
    QEQW,
    WeightedLeastSquares,
)
from porqua_tpu.constraints import Constraints
from porqua_tpu.estimators.mean import MeanEstimator
from porqua_tpu.optimization_data import OptimizationData
from porqua_tpu.qp import SolverParams

TIGHT = SolverParams(eps_abs=1e-9, eps_rel=1e-9, max_iter=20000)


@pytest.fixture
def market(rng):
    n = 8
    X = pd.DataFrame(
        rng.standard_normal((200, n)) * 0.01,
        index=pd.bdate_range("2022-01-03", periods=200),
        columns=[f"A{i}" for i in range(n)],
    )
    y = pd.Series(X.to_numpy() @ rng.dirichlet(np.ones(n)), index=X.index)
    return X, y


def constrained(opt, universe):
    opt.constraints = Constraints(selection=list(universe))
    opt.constraints.add_budget()
    opt.constraints.add_box("LongOnly")
    return opt


def test_qeqw_gives_equal_weights(market):
    """Identity covariance + zero mean under budget/box -> 1/N."""
    X, y = market
    opt = constrained(QEQW(dtype=jnp.float64, **TIGHT.__dict__), X.columns)
    opt.set_objective(OptimizationData(align=False, return_series=X))
    assert opt.solve()
    w = np.array(list(opt.results["weights"].values()))
    np.testing.assert_allclose(w, 1.0 / X.shape[1], atol=1e-7)


def test_mean_variance_risk_aversion_monotone(market):
    """Higher risk aversion -> lower portfolio variance."""
    X, y = market
    variances = []
    for ra in (0.5, 50.0):
        opt = constrained(
            MeanVariance(dtype=jnp.float64, risk_aversion=ra, **TIGHT.__dict__),
            X.columns,
        )
        opt.set_objective(OptimizationData(align=False, return_series=X))
        assert opt.solve()
        w = np.array(list(opt.results["weights"].values()))
        variances.append(float(w @ X.cov().to_numpy() @ w))
    assert variances[1] <= variances[0] + 1e-12


def test_weighted_least_squares_objective(market):
    """P/q must equal the exponentially-weighted normal equations."""
    X, y = market
    tau = 20.0
    opt = WeightedLeastSquares(tau=tau, dtype=jnp.float64, **TIGHT.__dict__)
    opt.set_objective(OptimizationData(align=False, return_series=X, bm_series=y))

    lam = np.exp(-np.log(2) / tau)
    wt_tmp = lam ** np.arange(len(X))
    wt = np.flip(wt_tmp / wt_tmp.sum() * len(wt_tmp))
    Xv, yv = X.to_numpy(), y.to_numpy()
    np.testing.assert_allclose(opt.objective["P"], 2 * Xv.T @ (wt[:, None] * Xv), atol=1e-12)
    np.testing.assert_allclose(opt.objective["q"], -2 * (wt[:, None] * Xv).T @ yv, atol=1e-12)


def test_wls_recent_emphasis(market):
    """With a short half-life, recently-shifted benchmarks move weights
    toward the recently-correlated asset."""
    X, y = market
    y2 = y.copy()
    y2.iloc[-40:] = X["A0"].iloc[-40:]  # benchmark becomes asset 0 lately
    opt = constrained(
        WeightedLeastSquares(tau=10.0, dtype=jnp.float64, **TIGHT.__dict__), X.columns
    )
    opt.set_objective(OptimizationData(align=False, return_series=X, bm_series=y2))
    assert opt.solve()
    w = opt.results["weights"]
    assert w["A0"] > 0.8


def test_lad_tracks_benchmark(market):
    X, y = market
    opt = constrained(
        LAD(dtype=jnp.float64, use_level=True, use_log=True, **TIGHT.__dict__),
        X.columns,
    )
    opt.set_objective(OptimizationData(align=False, return_series=X, bm_series=y))
    assert opt.solve()
    w = np.array(list(opt.results["weights"].values()))
    # LAD is an LP in epigraph form solved by first-order ADMM + polish
    # (flagged as LP territory by the reference too, optimization.py:286);
    # the budget row lands at ~1e-6-grade accuracy, data-dependent —
    # the exactness bar here is "budget to solver-noise", not 1e-9.
    assert abs(w.sum() - 1.0) < 1e-5
    assert w.min() > -1e-6
    # LAD minimizes the absolute level deviation: it must beat equal weight.
    lev_X = np.log((1 + X.to_numpy()).cumprod(axis=0))
    lev_y = np.log((1 + y.to_numpy()).cumprod())
    dev_lad = np.abs(lev_X @ w - lev_y).sum()
    dev_eq = np.abs(lev_X @ (np.ones(8) / 8) - lev_y).sum()
    assert dev_lad <= dev_eq + 1e-9


def test_percentile_portfolios_buckets(rng):
    scores = pd.Series(rng.standard_normal(25), index=[f"S{i}" for i in range(25)])
    pp = PercentilePortfolios(n_percentiles=5, estimator=MeanEstimator())
    pp.constraints = Constraints(selection=list(scores.index))
    X = pd.DataFrame(
        np.tile(scores.to_numpy(), (30, 1)) * 0.001,
        columns=scores.index,
    )
    pp.set_objective(OptimizationData(align=False, return_series=X))
    assert pp.solve()
    w = pd.Series(pp.results["weights"])
    # Long the top-mean bucket (score negated internally -> bucket 1 =
    # best), short the bottom; 5 assets in each on a 25-asset universe.
    assert (w > 0).sum() == 5 and (w < 0).sum() == 5
    assert w[w > 0].sum() == pytest.approx(1.0)
    assert w[w < 0].sum() == pytest.approx(-1.0)
    # The long bucket holds the highest-scoring names.
    top_names = scores.nlargest(5).index
    assert set(w[w > 0].index) == set(top_names)


def test_percentile_zero_score_noise_deterministic(rng):
    scores = pd.DataFrame({"s": np.zeros(10)}, index=[f"S{i}" for i in range(10)])
    outs = []
    for _ in range(2):
        pp = PercentilePortfolios(field="s", n_percentiles=5)
        pp.constraints = Constraints(selection=list(scores.index))
        pp.set_objective(OptimizationData(align=False, scores=scores))
        pp.solve()
        outs.append(pd.Series(pp.results["weights"]))
    pd.testing.assert_series_equal(outs[0], outs[1])


def test_percentile_results_carry_status_and_objective(rng):
    """Reference parity: the results dict always has "status" (reference
    ``optimization.py:86-87``) so Backtest.run's prev-weights bookkeeping
    fires, and an "objective" (top-minus-bottom raw-score spread) so
    append_custom's default keys record values (``backtest.py:245-270``)."""
    scores = pd.Series(rng.standard_normal(25), index=[f"S{i}" for i in range(25)])
    pp = PercentilePortfolios(n_percentiles=5, estimator=MeanEstimator())
    pp.constraints = Constraints(selection=list(scores.index))
    X = pd.DataFrame(
        np.tile(scores.to_numpy(), (30, 1)) * 0.001, columns=scores.index)
    pp.set_objective(OptimizationData(align=False, return_series=X))
    assert pp.solve()
    assert pp.results["status"] is True
    # Spread = mean(top-bucket scores) - mean(bottom-bucket scores) > 0.
    assert pp.results["objective"] > 0


def test_percentile_accepts_series_scores(rng):
    """A plain per-asset score vector (Series, not a one-column frame)
    is a natural way to hand a ranking signal to PercentilePortfolios;
    it must rank directly instead of crashing in the cross-column mean,
    and 'field' against a Series is a caller error, not a label lookup."""
    scores = pd.Series(rng.standard_normal(20), index=[f"S{i}" for i in range(20)])
    pp = PercentilePortfolios(n_percentiles=5)
    pp.constraints = Constraints(selection=list(scores.index))
    pp.set_objective(OptimizationData(align=False, scores=scores))
    assert pp.solve()
    w = pd.Series(pp.results["weights"])
    assert np.isclose(w[w > 0].sum(), 1.0) and np.isclose(w[w < 0].sum(), -1.0)

    pp_bad = PercentilePortfolios(field="s", n_percentiles=5)
    pp_bad.constraints = Constraints(selection=list(scores.index))
    with pytest.raises(ValueError, match="Series"):
        pp_bad.set_objective(OptimizationData(align=False, scores=scores))


def test_optimization_parameter_explicit_falsy_values_survive():
    """Key-presence defaulting: explicitly passing a falsy value must not
    silently re-default (the reference's truthiness quirk)."""
    from porqua_tpu.optimization import OptimizationParameter

    p = OptimizationParameter(solver_name="", verbose=False,
                              allow_suboptimal=False)
    assert p["solver_name"] == ""
    assert p["verbose"] is False
    assert p["allow_suboptimal"] is False
    # Defaults still apply when the keys are absent; allow_suboptimal
    # stays unmaterialized (absent == strict via .get()) so key
    # presence records whether the caller set it.
    d = OptimizationParameter()
    assert d["solver_name"] == "jax_admm"
    assert d["verbose"] is True
    assert "allow_suboptimal" not in d
    assert not d.get("allow_suboptimal")


def test_strategy_objectives_expose_gram_factor(market):
    """LeastSquares / WeightedLeastSquares / MeanVariance lower with the
    objective factor attached (P == 2 Pf'Pf + diag(Pdiag), verified by
    CanonicalQP.build), so the polish/capacitance paths see the
    structure through the strategy API, not just the tracking fast
    path. A lifted problem sheds the factor (it no longer reproduces
    the expanded P)."""
    X, y = market
    for opt in (
        constrained(LeastSquares(l2_penalty=0.1), X.columns),
        constrained(WeightedLeastSquares(tau=60), X.columns),
        constrained(MeanVariance(), X.columns),
    ):
        opt.set_objective(OptimizationData(
            align=False, return_series=X, bm_series=y))
        model = opt.model_canonical()
        assert model.Pf is not None, type(opt).__name__
        assert model.Pdiag is not None

    # Turnover-lifted problems drop the factor.
    lifted = constrained(
        LeastSquares(transaction_cost=0.002,
                     x0={c: 1.0 / len(X.columns) for c in X.columns}),
        X.columns,
    )
    lifted.set_objective(OptimizationData(
        align=False, return_series=X, bm_series=y))
    assert lifted.model_canonical().Pf is None


def test_is_feasible_ignores_objective_factor(market):
    """The feasibility probe replaces the objective; a factored
    objective (Pf) must be dropped with it, or the factored solver
    paths would probe against the real Hessian."""
    X, y = market
    opt = constrained(LeastSquares(), X.columns)
    opt.set_objective(OptimizationData(
        align=False, return_series=X, bm_series=y))
    assert opt.model_canonical().Pf is not None
    assert opt.is_feasible() is True

    infeasible = LeastSquares()
    infeasible.constraints = Constraints(selection=list(X.columns))
    infeasible.constraints.add_budget()               # sum w == 1 ...
    infeasible.constraints.add_box("LongOnly", upper=0.05)  # ... max 0.4
    infeasible.set_objective(OptimizationData(
        align=False, return_series=X, bm_series=y))
    assert infeasible.is_feasible() is False


def test_solver_name_dispatch(market):
    """Reference parity: solver_name routes to a named backend (the
    reference dispatches qpsolvers strings, optimization.py:45 +
    qp_problems.py:211). The f64 IPM and the native C++ core must agree
    with the default device solver; unknown names fail loudly."""
    X, y = market

    def solve_with(name):
        opt = constrained(LeastSquares(solver_name=name), X.columns)
        opt.set_objective(OptimizationData(
            align=False, return_series=X, bm_series=y))
        assert opt.solve(), name
        return np.array(list(opt.results["weights"].values()))

    w_default = solve_with("jax_admm")
    for name in ("ipm", "native"):
        w = solve_with(name)
        np.testing.assert_allclose(w, w_default, atol=5e-5, err_msg=name)

    opt = constrained(LeastSquares(solver_name="gurobi"), X.columns)
    opt.set_objective(OptimizationData(
        align=False, return_series=X, bm_series=y))
    with pytest.raises(ValueError, match="not available"):
        opt.solve()


def test_lad_prox_form_matches_ipm_objective():
    """LAD's default prox-form lowering (round 4: [w, s] variables,
    native L1 prox on the residual block, fixed LP step size) must
    reach the IPM oracle's objective on a mid-scale problem — the
    epigraph through adaptive-rho ADMM stalls at a double-digit
    percentage gap at scale (scripts/lad_scale_experiment.py)."""
    import jax.numpy as jnp

    from porqua_tpu.constraints import Constraints
    from porqua_tpu.optimization import LAD
    from porqua_tpu.qp.ipm import solve_ipm
    from porqua_tpu.tracking import synthetic_universe_np

    N, T = 120, 64
    Xs, ys = synthetic_universe_np(seed=13, n_dates=1, window=T,
                                   n_assets=N)
    X, y = Xs[0].astype(np.float64), ys[0].astype(np.float64)

    def build(**kw):
        lad = LAD(dtype=jnp.float64, **kw)
        cons = Constraints(selection=[f"a{i}" for i in range(N)])
        cons.add_budget()
        cons.add_box(lower=0.0, upper=1.0)
        lad.constraints = cons
        lad.objective = {"X": X, "y": y}
        return lad

    lad = build()
    sp = lad.solver_params()
    assert lad.params["prox_form"] and not sp.adaptive_rho
    assert sp.halpern and sp.rho0 == 60.0 and sp.max_iter == 40000
    assert sp.rho_l1_scale == 10.0
    assert sp.eps_abs == 1e-5  # f64 build() keeps the tight target
    # f32 (the device default) gets the floor-respecting 1e-4 overlay
    # unless the caller says otherwise; an f64-declared strategy solved
    # through an f32 batch (run_batch's default) must get it too.
    assert LAD().solver_params().eps_abs == 1e-4
    assert lad.solver_params(solve_dtype=jnp.float32).eps_abs == 1e-4
    # An explicit eps on either key pins BOTH to the caller's intent —
    # no half-relaxed configuration.
    tight32 = LAD(eps_abs=1e-6).solver_params()
    assert tight32.eps_abs == 1e-6 and tight32.eps_rel == 1e-5
    # The LP overlay must not leak into the shared params dict, and an
    # epigraph fallback (external backend) must not see it.
    assert "adaptive_rho" not in lad.params
    epi = build(solver_name="ipm")
    assert epi.solver_params().adaptive_rho  # SolverParams default
    assert lad.solve()
    w = np.asarray(lad.solution.x)[:N]
    obj = float(np.sum(np.abs(X @ w - y)))

    ipm = solve_ipm(build(prox_form=False).canonical_parts(), tol=1e-9)
    obj_ipm = float(np.sum(np.abs(X @ np.asarray(ipm.x)[:N] - y)))

    assert obj <= obj_ipm * (1 + 5e-3), (obj, obj_ipm)
    np.testing.assert_allclose(np.sum(w), 1.0, atol=1e-6)
    assert np.min(w) > -1e-5
