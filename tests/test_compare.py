"""Solver-comparison harness tests (``example/compare_solver.ipynb`` port).

The harness must (a) run every available backend on the identical
problem, (b) recompute all quality metrics uniformly from the returned
vectors, and (c) show the backends agreeing — the notebook's whole
point.
"""

import os

import numpy as np
import pytest

from porqua_tpu.compare import available_backends, compare_solvers, solution_metrics
from porqua_tpu.constraints import Constraints
from porqua_tpu.qp import SolverParams
from porqua_tpu.qp.canonical import CanonicalQP


@pytest.fixture(scope="module")
def tracking_qp():
    """Small index-tracking QP: budget + LongOnly box, upper 0.25."""
    rng = np.random.default_rng(17)
    T, n = 200, 8
    X = 0.01 * rng.standard_normal((T, n))
    w_true = rng.dirichlet(np.ones(n))
    y = X @ w_true + 0.001 * rng.standard_normal(T)
    P = 2.0 * X.T @ X
    q = -2.0 * X.T @ y
    cons = Constraints(selection=[f"A{i}" for i in range(n)])
    cons.add_budget()
    cons.add_box("LongOnly", upper=0.25)
    return cons.to_canonical(P=P, q=q, constant=float(y @ y))


def test_backends_available():
    names = set(available_backends())
    assert {"device-admm-f32", "device-admm-f64", "scipy-slsqp"} <= names
    assert "native-cpp-admm" in names  # g++ is in the image


def test_compare_solvers_agreement(tracking_qp):
    df = compare_solvers(tracking_qp)
    expected_cols = {"solution_found", "objective_value", "primal_residual",
                     "dual_residual", "duality_gap", "max_residual_Ab",
                     "max_residual_Gh", "runtime"}
    assert expected_cols <= set(df.columns)
    assert df["solution_found"].all(), df
    # accuracy: objective values agree across backends
    objs = df["objective_value"]
    assert objs.max() - objs.min() < 1e-5, objs
    # reliability: feasibility everywhere
    assert (df["primal_residual"] < 1e-5).all(), df["primal_residual"]
    assert (df["max_residual_Ab"] < 1e-6).all()
    # dual-side metrics exist where backends return duals
    for name in ("device-admm-f64", "native-cpp-admm"):
        assert df.loc[name, "dual_residual"] < 1e-6
        assert df.loc[name, "duality_gap"] < 1e-5
    # scipy returns no duals -> NaN, not an error
    assert np.isnan(df.loc["scipy-slsqp", "dual_residual"])


def test_compare_solvers_subset_and_unknown(tracking_qp):
    df = compare_solvers(tracking_qp, solvers=["device-admm-f32"])
    assert list(df.index) == ["device-admm-f32"]
    with pytest.raises(KeyError):
        compare_solvers(tracking_qp, solvers=["osqp-gpu"])


def test_solution_metrics_flags_violations(tracking_qp):
    from porqua_tpu.compare import _numpy_parts

    parts = _numpy_parts(tracking_qp)
    n = len(parts["q"])
    # deliberately infeasible point: violates budget and box
    x_bad = np.full(n, 2.0 / n)
    m = solution_metrics(parts, x_bad)
    assert m["primal_residual"] > 0.5  # budget off by 1.0
    assert m["max_residual_Ab"] > 0.5
    # feasible uniform point: only metrics near zero on constraints
    x_ok = np.full(n, 1.0 / n)
    m2 = solution_metrics(parts, x_ok)
    assert m2["primal_residual"] < 1e-12


@pytest.mark.skipif(not os.path.isdir("/root/reference/data/"),
                    reason="reference data mount not present")
def test_compare_on_msci_universe():
    """The notebook's cell-6 configuration on the real 24-asset universe."""
    import jax.numpy as jnp

    from porqua_tpu.data_loader import load_data_msci
    from porqua_tpu.optimization import LeastSquares
    from porqua_tpu.optimization_data import OptimizationData

    data = load_data_msci(path="/root/reference/data/")
    X = data["return_series"].tail(500)
    y = data["bm_series"].reindex(X.index).iloc[:, 0]
    universe = list(X.columns)

    opt = LeastSquares(dtype=jnp.float64)
    opt.constraints = Constraints(selection=universe)
    opt.constraints.add_budget()
    opt.constraints.add_box("LongOnly", upper=0.1)
    opt.set_objective(OptimizationData(align=False, return_series=X, bm_series=y))
    qp = opt.model_canonical()

    df = compare_solvers(qp)
    assert df["solution_found"].all()
    objs = df["objective_value"]
    assert objs.max() - objs.min() < 1e-6 * max(1.0, abs(objs.mean()))


def test_ipm_backend_registered():
    assert "ipm-f64" in available_backends()


def test_ipm_independent_agreement(tracking_qp):
    """VERDICT item 6: the interior-point reference is algorithmically
    independent of every ADMM implementation; ADMM/IPM objective
    agreement on the tracking problem must reach 1e-8."""
    df = compare_solvers(
        tracking_qp,
        solvers=["device-admm-f64", "ipm-f64"],
        params=SolverParams(eps_abs=1e-9, eps_rel=1e-9, max_iter=20000),
    )
    assert df["solution_found"].all(), df
    objs = df["objective_value"]
    assert objs.max() - objs.min() <= 1e-8, objs
    # The IPM reaches interior-point accuracy on its own metrics.
    assert df.loc["ipm-f64", "primal_residual"] < 1e-9
    assert df.loc["ipm-f64", "dual_residual"] < 1e-8
    assert df.loc["ipm-f64", "duality_gap"] < 1e-7


@pytest.mark.skipif(
    not os.path.exists("/root/reference/data/msci_country_indices.csv"),
    reason="reference data mount not present")
def test_ipm_msci_real_data():
    """IPM vs device ADMM on the real 24-country MSCI tracking problem
    (the compare_solver.ipynb cell-8 workload)."""
    import pandas as pd

    from porqua_tpu.data_loader import load_data_msci

    data = load_data_msci(path="/root/reference/data/")
    X = data["return_series"].tail(400)
    y = data["bm_series"].tail(400).to_numpy().ravel()
    Xv = X.to_numpy()
    P = 2.0 * Xv.T @ Xv
    q = -2.0 * Xv.T @ y
    cons = Constraints(selection=list(X.columns))
    cons.add_budget()
    cons.add_box("LongOnly")
    qp = cons.to_canonical(P=P, q=q, constant=float(y @ y))
    df = compare_solvers(
        qp, solvers=["device-admm-f64", "ipm-f64"],
        params=SolverParams(eps_abs=1e-9, eps_rel=1e-9, max_iter=20000),
    )
    assert df["solution_found"].all(), df
    objs = df["objective_value"]
    assert objs.max() - objs.min() <= 1e-8, objs
