"""Per-(bucket, eps) solver routing (porqua_tpu.serve.routing).

Host-side contracts (no compiles): constructor/force validation, the
harvest-seeded route table (solved share > dispatch latency >
iteration p95 > name — one-sided cells keep the default), decision
counters, the service/router params handshake.

One end-to-end service test (compiles two tiny ladders once): routed
serving returns correct answers under shadow-compare, prewarm covers
BOTH backends so a mid-stream force flip dispatches with zero new
compiles, per-tenant ``routed_*`` attribution lands in the metrics
snapshot, and shadow lanes reach the harvest warehouse as
``serve.shadow`` records carrying the loser's outcome + deltas.
"""

import dataclasses

import numpy as np
import pytest

from porqua_tpu.obs.harvest import HarvestSink, aggregate, solve_record
from porqua_tpu.qp.admm import Status
from porqua_tpu.qp.solve import SolverParams, solve_qp
from porqua_tpu.serve import Bucket, BucketLadder, SolveService
from porqua_tpu.serve.routing import METHODS, SolverRouter

from tests.test_serve import make_qp

PARAMS = SolverParams(max_iter=500, eps_abs=1e-5, eps_rel=1e-5,
                      polish=False, check_interval=25)
EPS = float(PARAMS.eps_abs)
PDHG = dataclasses.replace(PARAMS, method="pdhg")


def _records(bucket, method, n, *, iters, status=int(Status.SOLVED),
             solve_s=None):
    p = dataclasses.replace(PARAMS, method=method)
    return [solve_record("serve", 6, 2, status, iters, 1e-6, 1e-6,
                         -1.0, params=p, bucket=bucket, solve_s=solve_s)
            for _ in range(n)]


# ---------------------------------------------------------------------------
# validation
# ---------------------------------------------------------------------------

def test_router_validation():
    with pytest.raises(ValueError, match="unknown method"):
        SolverRouter(dataclasses.replace(PARAMS, method="qpth"))
    with pytest.raises(ValueError, match="shadow_rate"):
        SolverRouter(PARAMS, shadow_rate=1.5)
    router = SolverRouter(PARAMS)
    with pytest.raises(ValueError, match="unknown method"):
        router.force("qpth")
    # Per-backend caches differ exactly by method.
    assert set(router.caches) == set(METHODS)
    assert router.params_for("pdhg") == PDHG
    assert router.params == PARAMS


def test_service_router_handshake():
    from porqua_tpu.serve import ExecutableCache
    router = SolverRouter(PARAMS)
    with pytest.raises(ValueError, match="not both"):
        SolveService(PARAMS, router=router, cache=ExecutableCache(PARAMS))
    with pytest.raises(ValueError, match="different"):
        SolveService(dataclasses.replace(PARAMS, eps_abs=1e-3),
                     router=router)


# ---------------------------------------------------------------------------
# harvest-seeded routing
# ---------------------------------------------------------------------------

def test_seed_from_aggregate():
    recs = []
    # Cell 8x4: both solved, pdhg 5x lower dispatch latency -> pdhg.
    recs += _records("8x4", "admm", 10, iters=100, solve_s=5e-3)
    recs += _records("8x4", "pdhg", 10, iters=300, solve_s=1e-3)
    # Cell 16x4: pdhg is faster but runs out of iterations half the
    # time -> solved share rules, admm wins.
    recs += _records("16x4", "admm", 10, iters=100, solve_s=5e-3)
    recs += _records("16x4", "pdhg", 5, iters=500, solve_s=1e-3,
                     status=int(Status.MAX_ITER))
    recs += _records("16x4", "pdhg", 5, iters=400, solve_s=1e-3)
    # Cell 16x8: one-sided evidence -> no route written.
    recs += _records("16x8", "pdhg", 10, iters=50, solve_s=1e-3)
    # Cell 32x4: admm never recorded latency -> iteration p95 decides.
    recs += _records("32x4", "admm", 10, iters=100)
    recs += _records("32x4", "pdhg", 10, iters=40, solve_s=1e-3)

    router = SolverRouter(PARAMS)
    written = router.seed_from_aggregate(aggregate(recs))
    assert written == {f"8x4@{EPS:.0e}": "pdhg",
                       f"16x4@{EPS:.0e}": "admm",
                       f"32x4@{EPS:.0e}": "pdhg"}, written

    assert router.route(Bucket(8, 4, None)) == "pdhg"
    assert router.route(Bucket(16, 4, None)) == "admm"
    assert router.route(Bucket(32, 4, None)) == "pdhg"
    # One-sided and unseen cells fall back to the service default.
    assert router.route(Bucket(16, 8, None)) == "admm"
    assert router.route(Bucket(64, 4, None)) == "admm"
    assert router.decisions() == {"admm": 3, "pdhg": 2, "napg": 0}

    # decide() resolves to the matching backend's executable cache.
    method, cache = router.decide(Bucket(8, 4, None))
    assert method == "pdhg" and cache is router.caches["pdhg"]

    snap = router.snapshot()
    assert snap["table"][f"8x4@{EPS:.0e}"] == "pdhg"
    assert snap["forced"] is None and snap["default_method"] == "admm"


def test_force_overrides_table():
    router = SolverRouter(PARAMS)
    recs = (_records("8x4", "admm", 4, iters=100, solve_s=5e-3)
            + _records("8x4", "pdhg", 4, iters=50, solve_s=1e-3))
    router.seed_from_aggregate(aggregate(recs))
    b = Bucket(8, 4, None)
    assert router.route(b) == "pdhg"
    router.force("admm")
    assert router.route(b) == "admm"
    assert router.snapshot()["forced"] == "admm"
    router.force(None)
    assert router.route(b) == "pdhg"


def test_seed_pools_across_tenants():
    """Evidence for one (bucket, eps) cell pools across tenants — the
    compiled programs are tenant-blind, so the winner must be too."""
    recs = []
    for tenant in ("fund-a", "fund-b"):
        for method, s in (("admm", 5e-3), ("pdhg", 1e-3)):
            p = dataclasses.replace(PARAMS, method=method)
            recs += [solve_record("serve", 6, 2, 1, 100, 1e-6, 1e-6,
                                  -1.0, params=p, bucket="8x4",
                                  solve_s=s, tenant=tenant)
                     for _ in range(4)]
    agg = aggregate(recs)
    assert len([g for g in agg["groups"] if g["bucket"] == "8x4"]) == 2
    router = SolverRouter(PARAMS)
    assert router.seed_from_aggregate(agg) == {f"8x4@{EPS:.0e}": "pdhg"}


# ---------------------------------------------------------------------------
# versioned table swap (the calibration plane's mutation point)
# ---------------------------------------------------------------------------

def test_set_table_versioning():
    router = SolverRouter(PARAMS)
    assert router.table_version == 0
    with pytest.raises(ValueError, match="unknown method"):
        router.set_table({("8x4", EPS): "qpth"})
    assert router.table_version == 0           # failed swap: no bump

    assert router.set_table({("8x4", EPS): "pdhg"}) == 1
    assert router.route(Bucket(8, 4, None)) == "pdhg"
    assert router.table() == {("8x4", EPS): "pdhg"}
    # A swap to identical content is still a NEW version — versions
    # are never reused, so the audit chain replays linearly.
    assert router.set_table({("8x4", EPS): "pdhg"}) == 2
    assert router.set_table({}) == 3           # rollback-to-empty bumps
    assert router.route(Bucket(8, 4, None)) == "admm"
    # seed_from_aggregate shares the same version counter.
    recs = (_records("8x4", "admm", 4, iters=100, solve_s=5e-3)
            + _records("8x4", "pdhg", 4, iters=50, solve_s=1e-3))
    router.seed_from_aggregate(aggregate(recs))
    assert router.table_version == 4


# ---------------------------------------------------------------------------
# shadow budget
# ---------------------------------------------------------------------------

class _FakeShadowCache:
    """Stands in for the alternate backend's ExecutableCache so the
    budget accounting is pinned without a compile."""

    def __init__(self, params):
        self.params = params
        self.calls = 0

    def get(self, bucket, slots, dtype, device):
        self.calls += 1

        def exe(qp, x0, y0):
            import types
            return types.SimpleNamespace(
                status=np.array([1]), iters=np.array([10]),
                prim_res=np.array([1e-7]), dual_res=np.array([1e-7]),
                obj_val=np.array([0.5]))
        return exe


def test_shadow_budget_caps_and_defers():
    """shadow_budget_per_tick bounds evidence-gathering cost: sampled
    dispatches over budget are deferred (counted, no solve), and the
    calibration tick's reset_shadow_budget opens the next window."""
    with pytest.raises(ValueError, match="shadow_budget_per_tick"):
        SolverRouter(PARAMS, shadow_budget_per_tick=-1)

    import types
    from porqua_tpu.obs.calibrate import Calibrator
    router = SolverRouter(PARAMS, shadow_rate=1.0, shadow_seed=0,
                          shadow_budget_per_tick=2)
    fake = _FakeShadowCache(PDHG)
    # One fake serves every losing backend: the alt choice is sampled
    # among ALL losers now, and this test pins budget accounting, not
    # which loser won the draw.
    for alt in METHODS:
        if alt != "admm":
            router.caches[alt] = fake
    harvest = HarvestSink()
    cal = Calibrator()
    lane = types.SimpleNamespace(n_orig=6, m_orig=2, tenant=None)
    primary = {"status": np.array([1]), "iters": np.array([40]),
               "obj": np.array([0.4]), "solve_s": 4e-3}

    def shadow():
        return router.maybe_shadow(Bucket(8, 4, None), 1, None, None,
                                   None, None, None, "admm", primary,
                                   [lane], harvest, calibrator=cal)

    ran = [shadow() for _ in range(5)]
    assert ran == [True, True, False, False, False]
    snap = router.snapshot()
    assert snap["shadow_solves"] == 2 and snap["shadow_deferred"] == 3
    assert fake.calls == 2                     # deferred lanes never solve

    router.reset_shadow_budget()               # the calibration tick
    assert shadow() is True
    snap = router.snapshot()
    assert snap["shadow_solves"] == 3 and snap["shadow_deferred"] == 3
    assert snap["shadow_budget_per_tick"] == 2

    # Every shadow that RAN produced a serve.shadow record (with the
    # delta-vs-served fields) and fed the live calibrator.
    shadows = [r for r in harvest.buffered()
               if r["source"] == "serve.shadow"]
    assert len(shadows) == 3
    assert all(r["shadow_of"] == "admm" and r["delta_iters"] == -30
               and "delta_solve_s" in r for r in shadows)
    assert cal.counters()["calibration_observed"] == 3


# ---------------------------------------------------------------------------
# routed serving end to end
# ---------------------------------------------------------------------------

def test_routed_service_shadow_and_flip():
    qps = [make_qp(6, 2, seed=s) for s in range(6)]
    refs = [np.asarray(solve_qp(q, PARAMS).x) for q in qps]
    ladder = BucketLadder(n_rungs=(8,), m_rungs=(4,))
    harvest = HarvestSink()  # in-memory buffer
    router = SolverRouter(PARAMS, shadow_rate=1.0, shadow_seed=0)
    with SolveService(PARAMS, ladder=ladder, max_batch=2,
                      max_wait_ms=5.0, router=router,
                      harvest=harvest) as svc:
        # Prewarm compiles BOTH backends' ladders (2 slots x 2
        # methods x {solve}) — the flip below must not retrace.
        assert svc.prewarm(qps[0]) > 0
        compiles_warm = svc.snapshot()["compiles"]
        assert compiles_warm >= 4

        for q, ref, tenant in zip(qps[:4], refs[:4],
                                  ("fund-a", "fund-a", "fund-b", None)):
            r = svc.solve(q, timeout=120, tenant=tenant)
            np.testing.assert_allclose(r.x, ref, atol=5e-4)

        # Mid-stream force flip: the next dispatches run PDHG out of
        # the prewarmed cache — same answers, zero new compiles.
        router.force("pdhg")
        for q, ref in zip(qps[4:], refs[4:]):
            np.testing.assert_allclose(svc.solve(q, timeout=120).x,
                                       ref, atol=5e-4)
    # Snapshot after stop: shadows run on the dispatch thread after
    # the primary futures resolve, so an in-flight snapshot could
    # still miss the final shadow's accounting.
    snap = svc.snapshot()
    assert snap["compiles"] == compiles_warm
    assert snap["completed"] == 6 and snap["failed"] == 0
    assert snap["routed_admm"] >= 4 and snap["routed_pdhg"] >= 2
    # Per-tenant attribution.
    assert snap["tenants"]["fund-a"]["routed_admm"] == 2
    assert snap["tenants"]["fund-b"]["routed_admm"] == 1
    assert snap["shadow_solves"] >= 1

    rsnap = router.snapshot()
    assert rsnap["forced"] == "pdhg"
    assert rsnap["decisions"]["pdhg"] >= 2
    assert rsnap["shadow_solves"] == snap["shadow_solves"]
    assert rsnap["shadow_failures"] == 0

    # Shadow lanes landed in the warehouse as serve.shadow records
    # carrying the alternate backend's outcome + delta vs the served
    # answer — the evidence seed_from_aggregate consumes.
    shadows = [r for r in harvest.buffered()
               if r["source"] == "serve.shadow"]
    assert shadows, "shadow_rate=1.0 must shadow every dispatch"
    for r in shadows:
        assert r["shadow_of"] in METHODS
        assert r["solver"] in METHODS and r["solver"] != r["shadow_of"]
        assert isinstance(r["delta_iters"], int)
        assert isinstance(r["agree"], bool)
        assert r["bucket"] == "8x4"
    # Both served primaries observed (admm before the flip, pdhg
    # after); the shadowed loser is sampled among the OTHER two
    # backends, so napg appears as a solver, never as a primary here.
    assert {r["shadow_of"] for r in shadows} == {"admm", "pdhg"}
    # The aggregate's backend axis picks both solvers up.
    cell = next(g for g in aggregate(harvest.buffered())["groups"]
                if g.get("by_solver") and len(g["by_solver"]) > 1)
    assert set(cell["by_solver"]) <= set(METHODS)


# ---------------------------------------------------------------------------
# three-backend generalization (NAPG as third contender)
# ---------------------------------------------------------------------------

def test_seed_three_way_napg_wins_box_cell():
    """With three contenders in one cell the scoring is N-ary: NAPG's
    faster dispatch wins the box-only bucket over both incumbents, and
    a cell where only two of the three backends reported still
    compares (two-sided evidence is enough; three-sided is better)."""
    recs = []
    # Cell 8x1 (box+budget): all three solved, napg fastest.
    recs += _records("8x1", "admm", 10, iters=60, solve_s=4e-3)
    recs += _records("8x1", "pdhg", 10, iters=400, solve_s=6e-3)
    recs += _records("8x1", "napg", 10, iters=30, solve_s=8e-4)
    # Cell 16x4 (general rows): napg honestly retires MAX_ITER —
    # solved share rules it out even though its latency is lowest.
    recs += _records("16x4", "admm", 10, iters=80, solve_s=3e-3)
    recs += _records("16x4", "pdhg", 10, iters=200, solve_s=2e-3)
    recs += _records("16x4", "napg", 10, iters=500, solve_s=1e-3,
                     status=int(Status.MAX_ITER))
    # Cell 32x1: only admm + napg observed.
    recs += _records("32x1", "admm", 10, iters=70, solve_s=5e-3)
    recs += _records("32x1", "napg", 10, iters=25, solve_s=9e-4)

    router = SolverRouter(PARAMS)
    written = router.seed_from_aggregate(aggregate(recs))
    assert written == {f"8x1@{EPS:.0e}": "napg",
                       f"16x4@{EPS:.0e}": "pdhg",
                       f"32x1@{EPS:.0e}": "napg"}, written
    assert router.route(Bucket(8, 1, None)) == "napg"
    assert router.route(Bucket(16, 4, None)) == "pdhg"
    assert router.route(Bucket(32, 1, None)) == "napg"


def test_shadow_sampling_covers_all_losers():
    """shadow_rate=1.0 with a three-backend METHODS: every dispatch
    shadows, and the seeded loser draw exercises BOTH losing backends
    over a stream (no loser starves for evidence)."""
    import types
    router = SolverRouter(PARAMS, shadow_rate=1.0, shadow_seed=3)
    fakes = {}
    for alt in METHODS:
        if alt != "admm":
            fakes[alt] = _FakeShadowCache(
                dataclasses.replace(PARAMS, method=alt))
            router.caches[alt] = fakes[alt]
    harvest = HarvestSink()
    lane = types.SimpleNamespace(n_orig=6, m_orig=2, tenant=None)
    primary = {"status": np.array([1]), "iters": np.array([40]),
               "obj": np.array([0.4]), "solve_s": 4e-3}
    for _ in range(24):
        assert router.maybe_shadow(Bucket(8, 4, None), 1, None, None,
                                   None, None, None, "admm", primary,
                                   [lane], harvest)
    assert all(f.calls > 0 for f in fakes.values()), {
        m: f.calls for m, f in fakes.items()}
    solvers = {r["solver"] for r in harvest.buffered()
               if r["source"] == "serve.shadow"}
    assert solvers == {m for m in METHODS if m != "admm"}


def test_set_table_accepts_napg_routes():
    router = SolverRouter(PARAMS)
    v = router.set_table({("8x1", EPS): "napg", ("16x4", EPS): "pdhg"})
    assert v == 1
    assert router.route(Bucket(8, 1, None)) == "napg"
    with pytest.raises(ValueError, match="unknown method"):
        router.set_table({("8x1", EPS): "qpth"})
