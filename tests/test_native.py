"""Native C++ ADMM core vs the JAX device solver and analytic references."""

import numpy as np
import pytest

import jax.numpy as jnp

from porqua_tpu.native import build_library, solve_qp_native
from porqua_tpu.qp import SolverParams, Status, solve_qp
from porqua_tpu.qp.canonical import CanonicalQP


def test_builds():
    path = build_library()
    import os

    assert os.path.exists(path)


def test_native_unconstrained():
    rng = np.random.default_rng(0)
    n = 10
    Q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    P = (Q * np.logspace(0, 1, n)) @ Q.T
    q = rng.standard_normal(n)
    sol = solve_qp_native(P, q)
    assert sol.status == Status.SOLVED
    np.testing.assert_allclose(sol.x, -np.linalg.solve(P, q), atol=1e-6)


def test_native_matches_device_solver(rng):
    """Same portfolio QP through the C++ core and the JAX solver."""
    n = 20
    X = rng.standard_normal((80, n)) * 0.01
    P = 2 * X.T @ X + 1e-4 * np.eye(n)
    q = -0.01 * rng.random(n)
    C = np.ones((1, n))
    l = u = np.ones(1)
    lb, ub = np.zeros(n), np.ones(n)

    native = solve_qp_native(P, q, C, l, u, lb, ub)
    assert native.status == Status.SOLVED
    assert abs(native.x.sum() - 1.0) < 1e-6

    qp = CanonicalQP.build(P, q, C=C, l=l, u=u, lb=lb, ub=ub, dtype=jnp.float64)
    dev = solve_qp(qp, SolverParams(eps_abs=1e-9, eps_rel=1e-9, max_iter=20000))
    np.testing.assert_allclose(native.x, np.asarray(dev.x), atol=1e-5)
    assert native.obj_val == pytest.approx(
        float(dev.obj_val) - float(qp.constant), abs=1e-8
    )


def test_native_box_only(rng):
    n = 8
    P = np.eye(n)
    q = -2.0 * np.ones(n)
    sol = solve_qp_native(P, q, lb=np.zeros(n), ub=np.full(n, 0.5))
    assert sol.status == Status.SOLVED
    np.testing.assert_allclose(sol.x, 0.5, atol=1e-7)  # clipped optimum


def test_native_max_iter_reports():
    n = 4
    C = np.vstack([np.eye(n), np.eye(n)])
    l = np.concatenate([np.ones(n), np.full(n, -np.inf)])
    u = np.concatenate([np.full(n, np.inf), np.zeros(n)])
    sol = solve_qp_native(np.eye(n), np.zeros(n), C, l, u, max_iter=500)
    assert sol.status == Status.MAX_ITER  # infeasible -> cannot converge


def test_so_cache_falls_back_when_package_dir_readonly(monkeypatch, tmp_path):
    """A wheel installed into a read-only site-packages must still build
    and cache the native core — under the user cache dir (isolated to
    tmp_path here), keyed by source+arch so a stale or foreign-host
    binary is never reused."""
    import os

    import porqua_tpu.native as nat

    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    monkeypatch.setattr(nat.os, "access", lambda p, m: False)
    path = nat._so_path()
    assert path.startswith(str(tmp_path))
    assert not path.startswith(os.path.dirname(nat.__file__))
    # Same source + arch -> same key; the name embeds the hash.
    assert path == nat._so_path()
    assert os.path.basename(path).startswith("libporqua_qp-")
