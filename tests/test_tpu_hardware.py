"""Real-TPU evidence for the Pallas path (VERDICT round-1 item 5).

The default suite runs the Pallas kernel in interpret mode on CPU; the
f32 explicit-inverse segment with its rho clamp
(``porqua_tpu/qp/admm.py``) is precisely the code whose behavior
differs on hardware. These tests run it where it actually executes:

    PORQUA_TPU_TESTS=1 python -m pytest tests -m tpu -v

The session log is committed as ``TPU_TESTS_r{N}.txt`` each round.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from porqua_tpu.qp.admm import SolverParams
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.solve import Status, solve_qp

pytestmark = pytest.mark.tpu


def _tracking_qp(rng, n=128, T=160, dtype=jnp.float32):
    X = (rng.standard_normal((T, n)) * 0.01).astype(np.float32)
    w_true = rng.dirichlet(np.ones(n)).astype(np.float32)
    y = X @ w_true + (rng.standard_normal(T) * 0.001).astype(np.float32)
    P = 2.0 * X.T @ X
    q = -2.0 * X.T @ y
    return CanonicalQP.build(
        P, q, C=np.ones((1, n)), l=np.ones(1), u=np.ones(1),
        lb=np.zeros(n), ub=np.ones(n), dtype=dtype,
    ), X, y


def test_backend_is_tpu():
    assert jax.default_backend() == "tpu", jax.devices()


def test_pallas_kernel_parity_on_hardware(rng):
    """Non-interpreted Pallas segment vs the XLA triangular-solve path,
    both on the TPU chip: same problem, same optimum."""
    qp, X, y = _tracking_qp(rng)
    params = dict(eps_abs=1e-3, eps_rel=1e-3, max_iter=2000)
    sol_xla = solve_qp(qp, SolverParams(backend="xla", **params))
    sol_pal = solve_qp(qp, SolverParams(backend="pallas", **params))
    assert int(sol_xla.status) == Status.SOLVED
    assert int(sol_pal.status) == Status.SOLVED
    np.testing.assert_allclose(
        np.asarray(sol_pal.x), np.asarray(sol_xla.x), atol=5e-4)
    te_x = float(np.sqrt(np.mean((X @ np.asarray(sol_xla.x) - y) ** 2)))
    te_p = float(np.sqrt(np.mean((X @ np.asarray(sol_pal.x) - y) ** 2)))
    assert abs(te_x - te_p) <= 1e-5, (te_x, te_p)


def test_pallas_segment_matches_xla_iterations_on_hardware(rng):
    """Kernel-level parity: one fused segment == check_interval plain
    XLA iterations, run non-interpreted (the f32 explicit-inverse is the
    part interpret mode cannot vouch for)."""
    from jax.scipy.linalg import cho_factor, cho_solve

    from porqua_tpu.ops.admm_kernel import admm_segment
    from porqua_tpu.qp.ruiz import equilibrate

    qp, _, _ = _tracking_qp(rng, n=96, T=128)
    scaled, scaling = equilibrate(qp, iters=10)
    n, m = scaled.n, scaled.m
    dtype = scaled.P.dtype
    # Arbitrary per-row step size (both paths receive the same vector;
    # this is a kernel-parity test, not a convergence test).
    rho = jnp.full((m,), 100.0, dtype)
    rho_b = jnp.full((n,), 0.1, dtype)
    # 5 iterations: enough to exercise the fused segment end-to-end on
    # hardware while keeping f32 op-ordering drift (pallas vs XLA emit
    # different fusions) below a tight tolerance; full-solve parity at
    # 25-iteration segments is covered by
    # test_pallas_kernel_parity_on_hardware.
    sigma, alpha, iters = 1e-6, 1.6, 5

    K = (scaled.P + sigma * jnp.eye(n, dtype=dtype)
         + (scaled.C.T * rho) @ scaled.C + jnp.diag(rho_b))
    chol = cho_factor(K)
    Kinv = cho_solve(chol, jnp.eye(n, dtype=dtype))

    x = jnp.zeros(n, dtype)
    z = jnp.zeros(m, dtype)
    w = jnp.clip(x, scaled.lb, scaled.ub)
    y = jnp.zeros(m, dtype)
    mu = jnp.zeros(n, dtype)
    zeros = jnp.zeros(n, dtype)

    out = admm_segment(
        Kinv, scaled.C, scaled.q, scaled.l, scaled.u, scaled.lb, scaled.ub,
        rho, rho_b, zeros, zeros, x, z, w, y, mu,
        sigma=sigma, alpha=alpha, n_iters=iters, interpret=False,
    )

    # Plain XLA reference iterations (same explicit-inverse linear step
    # and the same HIGHEST matmul precision as the kernel, so the
    # comparison isolates the kernel, not factorization or bf16-pass
    # error).
    hp = jax.lax.Precision.HIGHEST

    def one(carry, _):
        x, z, w, y, mu = carry
        rhs = (sigma * x - scaled.q
               + jnp.dot(scaled.C.T, rho * z - y, precision=hp)
               + (rho_b * w - mu))
        xt = jnp.dot(rhs, Kinv, precision=hp)
        zt = jnp.dot(scaled.C, xt, precision=hp)
        x_new = alpha * xt + (1 - alpha) * x
        z_pre = alpha * zt + (1 - alpha) * z
        z_new = jnp.clip(z_pre + y / rho, scaled.l, scaled.u)
        y_new = y + rho * (z_pre - z_new)
        w_pre = alpha * xt + (1 - alpha) * w
        w_new = jnp.clip(w_pre + mu / rho_b, scaled.lb, scaled.ub)
        mu_new = mu + rho_b * (w_pre - w_new)
        return (x_new, z_new, w_new, y_new, mu_new), None

    (x_r, z_r, w_r, y_r, mu_r), _ = jax.lax.scan(
        one, (x, z, w, y, mu), None, length=iters)

    # Tolerances reflect f32 accumulation-order drift between the MXU
    # kernel and XLA's fusions through cond(K)-amplified matvecs —
    # measured ~6e-5 over 5 iterations on hardware; a real kernel bug
    # (wrong gate, wrong operand side, stale state) lands orders of
    # magnitude above this.
    for got, ref, tol in ((out[0], x_r, 3e-4), (out[2], w_r, 3e-4),
                          (out[4], mu_r, 3e-3)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=tol)


def test_rho_clamp_range_converges_on_hardware(rng):
    """The documented [1e-3, 1e2] rho clamp must keep the f32 explicit
    inverse usable across its whole range on the real MXU."""
    qp, _, _ = _tracking_qp(rng, n=128, T=160)
    for rho0 in (1e-3, 1e-1, 1e2):
        sol = solve_qp(qp, SolverParams(
            backend="pallas", rho0=rho0, adaptive_rho=False,
            eps_abs=1e-3, eps_rel=1e-3, max_iter=6000))
        assert int(sol.status) == Status.SOLVED, rho0
        assert float(sol.prim_res) < 1e-2


def test_factored_polish_grade_on_hardware(rng):
    """The exact-pinning factored polish (the default whenever the
    tracking QP carries its factor — qp/polish.py) must reach
    trinv-polish residual grade on the real chip in f32: this is the
    path the bench now times, and its capacitance solve (chol of the
    (T+m) matrix + Schur on the budget row) is precisely what interpret
    mode cannot vouch for."""
    from porqua_tpu.qp.polish import polish_capacitance_dim
    from porqua_tpu.qp.solve import SolverParams as SP
    from porqua_tpu.tracking import build_tracking_qp, synthetic_universe

    Xs, ys = synthetic_universe(
        jax.random.PRNGKey(11), n_dates=4, window=160, n_assets=256,
        dtype=jnp.float32)
    qp = jax.vmap(build_tracking_qp)(Xs, ys)
    assert polish_capacitance_dim(jax.tree.map(lambda a: a[0], qp)) == 161

    from porqua_tpu.qp.solve import solve_qp_batch

    sol = solve_qp_batch(qp, SP(eps_abs=1e-3, eps_rel=1e-3, max_iter=2000,
                                polish_passes=2))
    status = np.asarray(sol.status)
    assert int((status == Status.SOLVED).sum()) == 4, status
    # Contract: polish strictly improves on the 1e-3 ADMM exit grade on
    # every lane (accept-only-if-better), and lands most lanes near the
    # f32 floor. Hardware rounding can leave an occasional lane with an
    # accepted-but-partial improvement (measured one of four at ~5e-4),
    # so the max bound is the exit grade halved, the median the floor.
    pr = np.asarray(sol.prim_res)
    dr = np.asarray(sol.dual_res)
    assert float(np.max(np.maximum(pr, dr))) < 7e-4, (pr, dr)
    assert float(np.median(np.maximum(pr, dr))) < 5e-5, (pr, dr)


def test_steady_state_timer_sane_on_hardware():
    """measure_steady_state must return a positive per-step time well
    below the single-dispatch wall (which carries the tunnel RTT)."""
    from porqua_tpu.profiling import measure_device, measure_steady_state

    a = jnp.ones((64, 512, 512), jnp.float32)
    f = lambda x: jnp.sum(x @ x)
    per, floor = measure_steady_state(f, a, k=4, return_floor=True)
    single, _, _ = measure_device(jax.jit(f), a)
    # The per-step time must not exceed a dispatch (which carries the
    # transport's constant — ~70 ms through this container's tunnel,
    # ~0 on a PCIe host). The 25% slack absorbs timing noise on hosts
    # where the dispatch constant is negligible; no absolute floor is
    # asserted so the suite ports to either transport.
    assert 0.0 <= per <= single * 1.25
    assert floor >= 0.0


def test_bench_woodbury_config_matches_trinv_on_hardware():
    """The TPU headline config (capacitance/woodbury segments, refine 0,
    check_interval 35 — promoted in round 3 after the on-chip batch
    measured 35.0 ms vs trinv's 62.6 ms at B=252) must keep solving and
    match the trinv path's tracking error on a north-star shard. Pins
    the promotion against solver regressions: refine=0 is only sound at
    rho_eq_scale 1.0 (the library default since round 3)."""
    import dataclasses

    from porqua_tpu.qp.solve import SolverParams as SP
    from porqua_tpu.tracking import synthetic_universe_np, tracking_step_jit

    Xs_np, ys_np = synthetic_universe_np(
        seed=11, n_dates=16, window=252, n_assets=500)
    Xs, ys = jnp.asarray(Xs_np), jnp.asarray(ys_np)
    base = SP(eps_abs=1e-3, eps_rel=1e-3, max_iter=2000,
              polish=False, scaling_iters=2)
    wb = dataclasses.replace(base, linsolve="woodbury",
                             woodbury_refine=0, check_interval=35)
    out_t = tracking_step_jit(Xs, ys, base)
    out_w = tracking_step_jit(Xs, ys, wb)
    st_t, st_w = np.asarray(out_t.status), np.asarray(out_w.status)
    assert int((st_w == Status.SOLVED).sum()) == 16, st_w
    assert int((st_t == Status.SOLVED).sum()) == 16, st_t
    te_t = np.asarray(out_t.tracking_error)
    te_w = np.asarray(out_w.tracking_error)
    np.testing.assert_allclose(te_w, te_t, rtol=2e-3)


def test_northstar_shard_matched_tracking_error(rng):
    """A 16-date slice of the north-star shape (500 assets, window 252)
    solved on-chip: every date solves, and the f32+polish tracking error
    matches the f64 CPU-grade optimum within noise (the 'matched
    tracking error' acceptance bar)."""
    from porqua_tpu.qp.solve import SolverParams as SP
    from porqua_tpu.tracking import synthetic_universe_np, tracking_step_jit

    Xs_np, ys_np = synthetic_universe_np(
        seed=7, n_dates=16, window=252, n_assets=500)
    out = tracking_step_jit(
        jnp.asarray(Xs_np), jnp.asarray(ys_np),
        SP(eps_abs=1e-3, eps_rel=1e-3, max_iter=2000))
    status = np.asarray(out.status)
    assert int((status == Status.SOLVED).sum()) == 16, status

    # Independent f64 host reference on the first 4 dates (scipy SLSQP
    # is too slow at n=500; use the analytic equality-constrained
    # optimum projected by our own f64 numpy ADMM from bench.py).
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "benchmod", "/root/repo/bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    for i in range(4):
        X, y = Xs_np[i].astype(np.float64), ys_np[i].astype(np.float64)
        P = 2.0 * X.T @ X
        q = -2.0 * X.T @ y
        x_ref, _ = bench.admm_cpu(P, q, 0.0, 1.0, eps=1e-7, max_iter=20000)
        te_ref = float(np.sqrt(np.mean((X @ x_ref - y) ** 2)))
        te_dev = float(out.tracking_error[i])
        assert te_dev <= te_ref * 1.02 + 1e-6, (te_dev, te_ref)


def test_factored_scaling_headline_config_on_hardware():
    """Round-4 headline candidate: woodbury segments + factor-derived
    Jacobi scaling (scaling_mode="factored" — no dense-P Ruiz sweeps).
    Must solve every lane of a north-star shard with tracking error
    matching the Ruiz-scaled woodbury path."""
    import dataclasses

    from porqua_tpu.qp.solve import SolverParams as SP
    from porqua_tpu.tracking import synthetic_universe_np, tracking_step_jit

    Xs_np, ys_np = synthetic_universe_np(
        seed=11, n_dates=16, window=252, n_assets=500)
    Xs, ys = jnp.asarray(Xs_np), jnp.asarray(ys_np)
    wb = SP(eps_abs=1e-3, eps_rel=1e-3, max_iter=2000, polish=False,
            scaling_iters=2, linsolve="woodbury", woodbury_refine=0,
            check_interval=35)
    fac = dataclasses.replace(wb, scaling_mode="factored")
    out_r = tracking_step_jit(Xs, ys, wb)
    out_f = tracking_step_jit(Xs, ys, fac)
    assert int((np.asarray(out_f.status) == Status.SOLVED).sum()) == 16, (
        np.asarray(out_f.status))
    np.testing.assert_allclose(
        np.asarray(out_f.tracking_error), np.asarray(out_r.tracking_error),
        rtol=2e-3)


def test_factored_pallas_segment_on_hardware(rng):
    """The round-4 factored (capacitance) Pallas segment, compiled for
    real — the dense kernels VMEM-OOMed at n>=1000, this one keeps only
    (W, inv_d, Y0, Ginv) resident. Parity vs the XLA woodbury path on
    the same problems, non-interpreted."""
    import dataclasses

    from porqua_tpu.qp.solve import SolverParams as SP, solve_qp_batch
    from porqua_tpu.tracking import build_tracking_qp, synthetic_universe

    Xs, ys = synthetic_universe(
        jax.random.PRNGKey(4), n_dates=8, window=252, n_assets=500,
        dtype=jnp.float32)
    qps = jax.vmap(build_tracking_qp)(Xs, ys)
    kw = SP(eps_abs=1e-3, eps_rel=1e-3, max_iter=2000, polish=False,
            scaling_iters=2, linsolve="woodbury", woodbury_refine=0,
            check_interval=35, vmem_limit_mb=64.0)
    ref = solve_qp_batch(qps, kw)
    pal = solve_qp_batch(qps, dataclasses.replace(kw, backend="pallas"))
    assert int((np.asarray(pal.status) == Status.SOLVED).sum()) == 8, (
        np.asarray(pal.status))
    np.testing.assert_allclose(
        np.asarray(pal.x), np.asarray(ref.x), atol=5e-4)


def test_lad_halpern_prox_on_hardware(rng):
    """Round-5: the LAD prox lowering with its Halpern-anchored f32
    overlay (fixed rho 60, alpha 1.8, eps 1e-4 — the dtype-aware
    target that is actually reachable at the f32 residual floor),
    solved on the chip through the strategy layer. The epigraph
    lowering of the SAME problem is the objective cross-check."""
    from porqua_tpu.constraints import Constraints
    from porqua_tpu.optimization import LAD
    from porqua_tpu.qp.ipm import solve_ipm
    from porqua_tpu.tracking import synthetic_universe_np

    N, T = 128, 96
    Xs, ys = synthetic_universe_np(seed=17, n_dates=1, window=T,
                                   n_assets=N)
    X, y = Xs[0].astype(np.float64), ys[0].astype(np.float64)

    def build(**kw):
        lad = LAD(**kw)
        cons = Constraints(selection=[f"a{i}" for i in range(N)])
        cons.add_budget()
        cons.add_box(lower=0.0, upper=1.0)
        lad.constraints = cons
        lad.objective = {"X": X, "y": y}
        return lad

    lad = build()
    sp = lad.solver_params()
    assert sp.halpern and sp.eps_abs == 1e-4  # the promoted f32 config
    assert lad.solve()
    w = np.asarray(lad.solution.x)[:N]
    obj = float(np.sum(np.abs(X @ w - y)))
    # Device iterations must reflect the Halpern cut, not a stall.
    assert int(lad.solution.iters) < 20000, int(lad.solution.iters)

    # f64 IPM oracle on host (the chip solves f32; the oracle is the
    # accuracy yardstick, same pattern as the committed CPU evidence).
    ipm = solve_ipm(build(prox_form=False).canonical_parts(), tol=1e-9)
    obj_ipm = float(np.sum(np.abs(X @ np.asarray(ipm.x)[:N] - y)))
    assert obj <= obj_ipm * (1 + 5e-3), (obj, obj_ipm)
    np.testing.assert_allclose(np.sum(w), 1.0, atol=1e-4)
    assert float(np.min(w)) > -1e-3
