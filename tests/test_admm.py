"""ADMM solver correctness: parity vs scipy references and KKT checks.

This is the automated port of the reference's cross-solver validation
harness (``example/compare_solver.ipynb`` cells 6/8/12): the same
problem is solved by the TPU-native ADMM solver and an independent CPU
reference, comparing solutions, objective values, and primal/dual
residuals. qpsolvers/cvxopt are not available in this environment, so
the references are scipy (L-BFGS-B / SLSQP / linprog-HiGHS) and analytic
KKT solutions.
"""

import numpy as np
import jax.numpy as jnp
import pytest
import scipy.optimize

from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.solve import solve_qp, solve_qp_batch, SolverParams, Status

F64 = jnp.float64
TIGHT = SolverParams(eps_abs=1e-9, eps_rel=1e-9, max_iter=20000)


def random_psd(rng, n, cond=10.0):
    Q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    eigs = np.logspace(0, np.log10(cond), n)
    return (Q * eigs) @ Q.T


def test_unconstrained():
    """No active constraints: solution is -P^{-1} q."""
    rng = np.random.default_rng(0)
    n = 8
    P = random_psd(rng, n)
    q = rng.standard_normal(n)
    qp = CanonicalQP.build(P, q, dtype=F64)
    sol = solve_qp(qp, TIGHT)
    assert int(sol.status) == Status.SOLVED
    np.testing.assert_allclose(np.asarray(sol.x), -np.linalg.solve(P, q), atol=1e-6)


def test_equality_constrained_analytic():
    """Eq-constrained QP vs the analytic KKT solution."""
    rng = np.random.default_rng(1)
    n, me = 10, 3
    P = random_psd(rng, n)
    q = rng.standard_normal(n)
    A = rng.standard_normal((me, n))
    b = rng.standard_normal(me)
    qp = CanonicalQP.build(P, q, C=A, l=b, u=b, dtype=F64)
    sol = solve_qp(qp, TIGHT)
    assert int(sol.status) == Status.SOLVED

    kkt = np.block([[P, A.T], [A, np.zeros((me, me))]])
    ref = np.linalg.solve(kkt, np.concatenate([-q, b]))
    np.testing.assert_allclose(np.asarray(sol.x), ref[:n], atol=1e-6)
    # Dual parity too (sign convention: P x + q + A' y = 0)
    np.testing.assert_allclose(np.asarray(sol.y[:me]), ref[n:], atol=1e-5)


def test_box_constrained_vs_lbfgsb():
    rng = np.random.default_rng(2)
    n = 12
    P = random_psd(rng, n, cond=100.0)
    q = rng.standard_normal(n) * 3
    lb, ub = -0.3 * np.ones(n), 0.4 * np.ones(n)
    qp = CanonicalQP.build(P, q, lb=lb, ub=ub, dtype=F64)
    sol = solve_qp(qp, TIGHT)
    assert int(sol.status) == Status.SOLVED

    ref = scipy.optimize.minimize(
        lambda x: 0.5 * x @ P @ x + q @ x,
        x0=np.zeros(n),
        jac=lambda x: P @ x + q,
        bounds=list(zip(lb, ub)),
        method="L-BFGS-B",
        options={"ftol": 1e-15, "gtol": 1e-12, "maxiter": 5000},
    )
    np.testing.assert_allclose(np.asarray(sol.x), ref.x, atol=1e-6)


def portfolio_qp(rng, n, dtype=F64, n_max=None, m_max=None):
    """Long-only fully-invested min-variance-style problem."""
    X = rng.standard_normal((80, n)) * 0.01
    P = 2 * X.T @ X + 1e-4 * np.eye(n)
    q = -0.01 * rng.random(n)
    C = np.ones((1, n))
    return CanonicalQP.build(
        P, q, C=C, l=np.ones(1), u=np.ones(1),
        lb=np.zeros(n), ub=np.ones(n), dtype=dtype,
        n_max=n_max, m_max=m_max,
    ), P, q


def test_portfolio_vs_slsqp():
    rng = np.random.default_rng(3)
    n = 15
    qp, P, q = portfolio_qp(rng, n)
    sol = solve_qp(qp, TIGHT)
    assert int(sol.status) == Status.SOLVED
    assert float(jnp.sum(sol.x)) == pytest.approx(1.0, abs=1e-7)
    assert float(jnp.min(sol.x)) >= -1e-8

    ref = scipy.optimize.minimize(
        lambda x: 0.5 * x @ P @ x + q @ x,
        x0=np.ones(n) / n,
        jac=lambda x: P @ x + q,
        bounds=[(0, 1)] * n,
        constraints=[{"type": "eq", "fun": lambda x: x.sum() - 1,
                      "jac": lambda x: np.ones(n)}],
        method="SLSQP",
        options={"ftol": 1e-14, "maxiter": 1000},
    )
    assert float(sol.obj_val) <= ref.fun + 1e-8
    np.testing.assert_allclose(np.asarray(sol.x), ref.x, atol=1e-5)


def test_padded_solution_matches_unpadded():
    rng = np.random.default_rng(4)
    n = 10
    qp, _, _ = portfolio_qp(rng, n)
    rng = np.random.default_rng(4)
    qp_pad, _, _ = portfolio_qp(rng, n, n_max=16, m_max=6)
    sol = solve_qp(qp, TIGHT)
    sol_pad = solve_qp(qp_pad, TIGHT)
    assert int(sol_pad.status) == Status.SOLVED
    np.testing.assert_allclose(
        np.asarray(sol_pad.x[:n]), np.asarray(sol.x), atol=1e-7
    )
    np.testing.assert_allclose(np.asarray(sol_pad.x[n:]), 0.0, atol=1e-9)


def test_lp_vs_linprog():
    """P = 0 (pure LP, the LAD case) vs scipy's HiGHS."""
    rng = np.random.default_rng(5)
    n, m = 8, 5
    c = rng.random(n) + 0.1
    G = rng.standard_normal((m, n))
    h = rng.random(m) + 1.0
    qp = CanonicalQP.build(
        np.zeros((n, n)), c,
        C=G, l=np.full(m, -np.inf), u=h,
        lb=np.zeros(n), ub=np.ones(n), dtype=F64,
    )
    sol = solve_qp(qp, TIGHT)
    assert int(sol.status) == Status.SOLVED
    ref = scipy.optimize.linprog(c, A_ub=G, b_ub=h, bounds=[(0, 1)] * n)
    assert ref.status == 0
    assert float(sol.obj_val) == pytest.approx(ref.fun, abs=1e-6)


def test_batch_matches_single():
    rng = np.random.default_rng(6)
    qps = [portfolio_qp(rng, 12)[0] for _ in range(4)]
    batch = stack_qps(qps)
    bsol = solve_qp_batch(batch, TIGHT)
    for i, qp in enumerate(qps):
        s = solve_qp(qp, TIGHT)
        np.testing.assert_allclose(
            np.asarray(bsol.x[i]), np.asarray(s.x), atol=1e-6
        )
        assert int(bsol.status[i]) == Status.SOLVED


def test_primal_infeasible():
    """x >= 1 and x <= 0 simultaneously."""
    n = 4
    C = np.vstack([np.eye(n), np.eye(n)])
    l = np.concatenate([np.ones(n), np.full(n, -np.inf)])
    u = np.concatenate([np.full(n, np.inf), np.zeros(n)])
    qp = CanonicalQP.build(np.eye(n), np.zeros(n), C=C, l=l, u=u, dtype=F64)
    sol = solve_qp(qp, SolverParams(max_iter=4000))
    assert int(sol.status) == Status.PRIMAL_INFEASIBLE


def test_dual_infeasible():
    """Unbounded below: min -x, x >= 0 only."""
    n = 3
    qp = CanonicalQP.build(
        np.zeros((n, n)), -np.ones(n),
        lb=np.zeros(n), ub=np.full(n, np.inf), dtype=F64,
    )
    sol = solve_qp(qp, SolverParams(max_iter=4000))
    assert int(sol.status) == Status.DUAL_INFEASIBLE


def test_float32_accuracy():
    """f32 (the TPU path) with polish should still give ~1e-4 accuracy."""
    rng = np.random.default_rng(7)
    n = 20
    qp64, P, q = portfolio_qp(rng, n, dtype=F64)
    rng = np.random.default_rng(7)
    qp32, _, _ = portfolio_qp(rng, n, dtype=jnp.float32)
    ref = solve_qp(qp64, TIGHT)
    sol = solve_qp(qp32, SolverParams(eps_abs=1e-6, eps_rel=1e-6, max_iter=10000))
    assert int(sol.status) == Status.SOLVED
    np.testing.assert_allclose(
        np.asarray(sol.x), np.asarray(ref.x), atol=5e-4
    )


def test_warm_start_reduces_iterations():
    rng = np.random.default_rng(8)
    qp, _, _ = portfolio_qp(rng, 15)
    cold = solve_qp(qp, TIGHT)
    warm = solve_qp(qp, TIGHT, x0=cold.x, y0=cold.y)
    assert int(warm.iters) <= int(cold.iters)
    np.testing.assert_allclose(np.asarray(warm.x), np.asarray(cold.x), atol=1e-6)


class TestBackendSelection:
    """ADVICE fixes: VMEM gating of the fused Pallas segment and the
    warnings around backend overrides."""

    def _qp(self, rng, n=8):
        P = random_psd(rng, n)
        q = rng.standard_normal(n)
        return CanonicalQP.build(P, q, lb=np.zeros(n), ub=np.ones(n),
                                 dtype=F64)

    def test_auto_gates_on_vmem(self, rng):
        """A problem whose Kinv + C footprint exceeds the VMEM budget
        must not select the fused kernel under backend='auto'."""
        import jax

        from porqua_tpu.qp.admm import SolverParams as SP

        n = 64
        qp = self._qp(rng, n)
        bytes_needed = (n * n + qp.m * n + 16 * (n + qp.m)) * 8
        # Budget below the footprint: auto must take the XLA path even
        # if the default backend were TPU. On CPU this is trivially the
        # XLA path; the observable contract here is that the solve runs
        # and converges with an arbitrarily small budget (i.e. the gate
        # never leaves auto without a usable path).
        small = SP(eps_abs=1e-8, eps_rel=1e-8, max_iter=10000,
                   vmem_limit_mb=bytes_needed / 2**20 / 2)
        sol = solve_qp(qp, small)
        assert int(sol.status) == Status.SOLVED

    def test_explicit_pallas_warns_over_budget(self, rng):
        import warnings as _w

        from porqua_tpu.qp.admm import SolverParams as SP

        qp = self._qp(rng, n=16)
        params = SP(backend="pallas", vmem_limit_mb=1e-4, max_iter=200)
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            solve_qp(qp, params)
        msgs = [str(r.message) for r in rec]
        assert any("VMEM footprint" in m for m in msgs), msgs
        # Non-TPU host: interpret-mode warning fires too.
        assert any("interpret mode" in m for m in msgs), msgs

    def test_pallas_rho_clamp_warns_when_caller_tuned(self, rng):
        import warnings as _w

        import jax.numpy as jnp

        from porqua_tpu.qp.admm import SolverParams as SP

        n = 16
        P = random_psd(rng, n).astype(np.float32)
        q = rng.standard_normal(n).astype(np.float32)
        qp = CanonicalQP.build(P, q, lb=np.zeros(n), ub=np.ones(n),
                               dtype=jnp.float32)
        params = SP(backend="pallas", rho_min=1e-9, rho_max=1e9,
                    max_iter=200)
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            solve_qp(qp, params)
        assert any("adaptive-rho clamp" in str(r.message) for r in rec)


def test_blocked_triangular_inverse_matches_flat():
    # The recursion must reproduce the flat n-step substitution to
    # roundoff for awkward sizes (odd splits, below-threshold, batched).
    import jax
    import jax.numpy as jnp
    from jax.scipy.linalg import solve_triangular

    from porqua_tpu.qp.admm import blocked_triangular_inverse

    for n in (500, 253, 64):
        A = jax.random.normal(jax.random.PRNGKey(n), (3, n, n),
                              jnp.float64) * 0.1
        K = jnp.einsum("bij,bkj->bik", A, A) + 0.5 * jnp.eye(n)
        L = jnp.linalg.cholesky(K)
        ref = jax.vmap(lambda Li: solve_triangular(
            Li, jnp.eye(n, dtype=Li.dtype), lower=True))(L)
        got = blocked_triangular_inverse(L)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=0, atol=1e-12)
        # strictly lower-triangular output, zero upper block
        assert float(jnp.max(jnp.abs(jnp.triu(got, k=1)))) == 0.0


def test_f32_auto_resolves_to_trinv_at_scale():
    # Production-scale f32 regression: the cho_solve substitution's f32
    # error floor (~5e-3 primal at n=500) stalls ADMM above eps, while
    # the trinv apply converges in one segment. "auto" must therefore
    # pick trinv for f32 on every backend — this solves the same
    # problem the chol path measurably cannot.
    import jax

    from porqua_tpu.qp.admm import resolve_linsolve
    from porqua_tpu.tracking import build_tracking_qp, synthetic_universe_np

    Xs_np, ys_np = synthetic_universe_np(seed=42, n_dates=2, window=252,
                                         n_assets=500)
    Xs = jnp.asarray(Xs_np, jnp.float32)
    ys = jnp.asarray(ys_np, jnp.float32)
    qp = jax.vmap(build_tracking_qp)(Xs, ys)
    params = SolverParams(max_iter=2000, eps_abs=1e-3, eps_rel=1e-3,
                          polish_passes=1, scaling_iters=4)
    assert resolve_linsolve(
        params, jax.tree.map(lambda a: a[0], qp)) == "trinv"
    sol = solve_qp_batch(qp, params)
    assert np.all(np.asarray(sol.status) == 1), np.asarray(sol.status)
    assert np.all(np.asarray(sol.iters) <= 100)
