"""Unified observability (porqua_tpu.obs): span recorder + Chrome
trace export, event bus, Prometheus exposition + HTTP endpoint,
on-device convergence rings, and the end-to-end traced serve path.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from porqua_tpu.obs import (
    EventBus,
    Observability,
    ObsHTTPServer,
    SpanRecorder,
    load_jsonl,
    prometheus_text,
)
from porqua_tpu.obs.report import (
    coverage_stats,
    render_report,
    span_aggregate,
    sparkline,
)
from porqua_tpu.obs.rings import ring_history, solution_ring_history
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.solve import SolverParams, solve_qp, solve_qp_batch


def make_qp(n=6, m=2, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * n, n))
    P = A.T @ A / (2 * n) + np.eye(n)
    q = rng.standard_normal(n)
    C = np.concatenate([np.ones((1, n)), rng.standard_normal((m - 1, n))])
    return CanonicalQP.build(
        P, q, C=C, l=np.full(m, -1.0), u=np.ones(m),
        lb=np.zeros(n), ub=np.ones(n), dtype=dtype)


# ---------------------------------------------------------------------------
# span recorder
# ---------------------------------------------------------------------------

class TestSpanRecorder:
    def test_record_and_chrome_export(self):
        rec = SpanRecorder()
        tid = rec.new_trace()
        assert tid != rec.new_trace()  # unique per mint
        rec.record("queue_wait", 1.0, 1.5, trace_id=tid, bucket="8x4")
        with rec.span("solve", trace_id=tid):
            pass
        spans = rec.spans()
        assert [s.name for s in spans] == ["queue_wait", "solve"]
        assert spans[0].duration == pytest.approx(0.5)

        trace = rec.chrome_trace()
        events = trace["traceEvents"]
        assert len(events) == 2
        for e in events:
            assert e["ph"] == "X"
            assert e["args"]["trace_id"] == tid
        assert events[0]["dur"] == pytest.approx(0.5e6)
        assert events[0]["args"]["bucket"] == "8x4"
        # Loadable: a straight json round-trip preserves the structure.
        again = json.loads(json.dumps(trace))
        assert len(again["traceEvents"]) == 2

    def test_bounded_capacity_counts_drops(self):
        rec = SpanRecorder(capacity=2)
        for i in range(5):
            rec.record("s", i, i + 1)
        assert len(rec.spans()) == 2
        assert rec.dropped == 3
        assert rec.chrome_trace()["metadata"]["dropped_spans"] == 3

    def test_by_trace_groups_chronologically(self):
        rec = SpanRecorder()
        t1, t2 = rec.new_trace(), rec.new_trace()
        rec.record("b", 2.0, 3.0, trace_id=t1)
        rec.record("a", 1.0, 2.0, trace_id=t1)
        rec.record("a", 1.0, 2.0, trace_id=t2)
        rec.record("anon", 0.0, 1.0)  # no trace id: excluded
        grouped = rec.by_trace()
        assert set(grouped) == {t1, t2}
        assert [s.name for s in grouped[t1]] == ["a", "b"]


# ---------------------------------------------------------------------------
# event bus
# ---------------------------------------------------------------------------

class TestEventBus:
    def test_emit_filter_and_jsonl(self, tmp_path):
        bus = EventBus()
        bus.emit("compile", "info", bucket="8x4", seconds=0.1)
        bus.emit("breaker_open", "error", trace_id="t-1", failures=2)
        bus.emit("deadline_expired", "warn")
        assert len(bus.events()) == 3
        assert [e["kind"] for e in bus.events(min_severity="warn")] == [
            "breaker_open", "deadline_expired"]
        assert bus.events(kind="compile")[0]["bucket"] == "8x4"
        assert bus.events(kind="breaker_open")[0]["trace_id"] == "t-1"

        path = tmp_path / "events.jsonl"
        assert bus.write_jsonl(str(path)) == 3
        back = load_jsonl(str(path))
        assert [e["kind"] for e in back] == [
            "compile", "breaker_open", "deadline_expired"]

    def test_bounded_keeps_newest_and_coerces_severity(self):
        bus = EventBus(capacity=2)
        for i in range(4):
            bus.emit("e", "not-a-severity", i=i)
        assert len(bus.events()) == 2
        assert bus.dropped == 2
        # Ring semantics: the NEWEST events survive (the breaker flip
        # that just happened is what a diagnostic read needs).
        assert [e["i"] for e in bus.events()] == [2, 3]
        assert bus.events()[0]["severity"] == "info"  # coerced

    def test_streaming_sink(self, tmp_path):
        path = tmp_path / "stream.jsonl"
        bus = EventBus(path=str(path))
        bus.emit("a")
        bus.emit("b")
        bus.close()
        assert [e["kind"] for e in load_jsonl(str(path))] == ["a", "b"]


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------

class TestExposition:
    def test_prometheus_text_types_and_device(self):
        from porqua_tpu.serve import ServeMetrics

        m = ServeMetrics()
        m.inc("submitted", 3)
        m.observe_latency(0.01)
        m.set_device("cpu:0", degraded=True)
        text = prometheus_text(m.snapshot())
        assert "# TYPE porqua_serve_submitted counter" in text
        assert "porqua_serve_submitted 3" in text
        assert "# TYPE porqua_serve_latency_p50_ms gauge" in text
        assert "porqua_serve_degraded 1" in text
        assert 'porqua_serve_device_info{device="cpu:0"} 1' in text
        # No free-form strings leak in as metric samples.
        for line in text.splitlines():
            if not line.startswith("#") and "device_info" not in line:
                float(line.rsplit(" ", 1)[1])

    def test_http_server_metrics_and_healthz(self):
        health = {"ok": True, "degraded": False}
        srv = ObsHTTPServer(lambda: "m 1\n", lambda: health, port=0)
        port = srv.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10).read()
            assert body == b"m 1\n"
            got = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10).read())
            assert got["ok"] is True
            health["ok"] = False  # unhealthy flips to 503
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10)
            assert exc.value.code == 503
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=10)
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# convergence rings
# ---------------------------------------------------------------------------

class TestConvergenceRings:
    def test_default_program_has_no_rings(self):
        sol = solve_qp(make_qp(), SolverParams(polish=False))
        assert sol.ring_prim is None
        assert solution_ring_history(sol, 25) is None

    def test_ring_matches_final_residuals(self):
        """The acceptance bar: the last ring sample IS the reported
        final residual pair (polish off — the rings record the ADMM
        iterate; the post-polish recompute is a different point)."""
        params = SolverParams(polish=False, ring_size=8)
        sol = solve_qp(make_qp(seed=3), params)
        assert int(sol.status) == 1
        hist = solution_ring_history(sol, params.check_interval)
        assert hist["iters"][-1] == int(sol.iters)
        assert hist["prim_res"][-1] == pytest.approx(float(sol.prim_res),
                                                     rel=0, abs=0)
        assert hist["dual_res"][-1] == pytest.approx(float(sol.dual_res),
                                                     rel=0, abs=0)
        # Residuals decay along the trajectory; rho starts at rho0.
        assert hist["prim_res"][0] > hist["prim_res"][-1]
        assert hist["rho"][0] == pytest.approx(params.rho0)

    def test_ring_solution_identical_to_default(self):
        """ring_size only APPENDS outputs: x/status/iters are bitwise
        the program the flag did not exist for."""
        base = solve_qp(make_qp(seed=5), SolverParams(polish=False))
        ringed = solve_qp(make_qp(seed=5),
                          SolverParams(polish=False, ring_size=4))
        np.testing.assert_array_equal(np.asarray(base.x),
                                      np.asarray(ringed.x))
        assert int(base.iters) == int(ringed.iters)

    def test_ring_batched(self):
        params = SolverParams(polish=False, ring_size=6)
        qps = [make_qp(seed=s) for s in (7, 8, 9)]
        from porqua_tpu.qp.canonical import stack_qps

        sol = solve_qp_batch(stack_qps(qps), params)
        assert np.asarray(sol.ring_prim).shape == (3, 6)
        for i in range(3):
            hist = solution_ring_history(sol, params.check_interval,
                                         index=i)
            assert hist["prim_res"][-1] == pytest.approx(
                float(np.asarray(sol.prim_res)[i]), rel=0, abs=0)

    def test_ring_history_wraparound(self):
        """Synthetic decode check: 5 segments into a 3-ring keeps the
        last 3 samples in chronological order."""
        K, ci = 3, 25
        prim = np.zeros(K)
        dual = np.zeros(K)
        rho = np.zeros(K)
        for j in range(5):  # segment j writes slot j % K
            prim[j % K] = 10.0 ** -(j + 1)
            dual[j % K] = 10.0 ** -(j + 2)
            rho[j % K] = j + 1.0
        hist = ring_history(prim, dual, rho, iters=5 * ci,
                            check_interval=ci)
        assert hist["iters"] == [3 * ci, 4 * ci, 5 * ci]
        assert hist["prim_res"] == pytest.approx([1e-3, 1e-4, 1e-5])
        assert hist["rho"] == pytest.approx([3.0, 4.0, 5.0])


# ---------------------------------------------------------------------------
# traced serve path end to end
# ---------------------------------------------------------------------------

class TestTracedService:
    def test_spans_tile_request_wallclock_and_events_flow(self):
        from porqua_tpu.serve import BucketLadder, SolveService

        obs = Observability()
        params = SolverParams(max_iter=500, eps_abs=1e-5, eps_rel=1e-5,
                              polish=False, ring_size=4)
        svc = SolveService(params=params,
                           ladder=BucketLadder((8, 16), (4, 8)),
                           max_batch=4, max_wait_ms=5.0, obs=obs)
        with svc:
            results = [svc.solve(make_qp(seed=s), timeout=120)
                       for s in range(4)]
        assert all(r.found for r in results)
        # Every result carries its trace id + rings.
        for r in results:
            assert r.trace_id is not None
            assert r.ring_prim is not None
            hist = ring_history(r.ring_prim, r.ring_dual, r.ring_rho,
                                r.iters, params.check_interval)
            # Within f32 rounding: the AOT serve program may fuse the
            # segment's residual check and the final recompute
            # differently (observed one-ulp differences), unlike the
            # jit path where the two are bitwise equal.
            assert hist["prim_res"][-1] == pytest.approx(r.prim_res,
                                                         rel=1e-5)
            assert hist["dual_res"][-1] == pytest.approx(r.dual_res,
                                                         rel=1e-5)
        # Spans: the 5-stage pipeline per request, tiling its life.
        grouped = obs.spans.by_trace()
        ids = {r.trace_id for r in results}
        assert ids <= set(grouped)
        for r in results:
            spans = grouped[r.trace_id]
            assert [s.name for s in spans] == [
                "submit", "queue_wait", "assemble", "solve", "resolve"]
            total = sum(s.duration for s in spans)
            extent = spans[-1].t_end - spans[0].t_start
            assert total == pytest.approx(extent, rel=1e-6)
            # ...and the instrumented latency is inside the extent.
            assert r.latency_s <= extent + 1e-6
        cov = coverage_stats(obs.spans.chrome_trace())
        assert cov["cover_median"] == pytest.approx(1.0, abs=1e-6)
        # Events: the prewarm-less cold path logged its compiles.
        compiles = obs.events.events(kind="compile")
        assert compiles and all(e["severity"] == "info" for e in compiles)

    def test_expiry_and_backpressure_events(self):
        from porqua_tpu.serve import (BucketLadder, QueueFull,
                                      SolveService)

        obs = Observability()
        params = SolverParams(max_iter=200, polish=False)
        svc = SolveService(params=params,
                           ladder=BucketLadder((8, 16), (4, 8)),
                           max_batch=4, max_wait_ms=150.0,
                           queue_capacity=1, obs=obs)
        svc._started = True  # no batcher: force queue/deadline paths
        svc.submit(make_qp(seed=1))
        with pytest.raises(QueueFull):
            svc.submit(make_qp(seed=2), timeout=0.05)
        rejects = obs.events.events(kind="backpressure_reject")
        assert len(rejects) == 1 and rejects[0]["severity"] == "warn"

        import time as _time
        from concurrent.futures import Future

        from porqua_tpu.serve.batcher import DeadlineExpired, SolveRequest

        # Feed one already-expired request straight into the dispatch.
        bucket, padded = svc.ladder.pad(make_qp(seed=3))
        now = _time.monotonic()
        req = SolveRequest(qp=padded, bucket=bucket, n_orig=6, m_orig=2,
                           future=Future(), submitted=now - 1.0,
                           deadline=now - 0.5,
                           trace_id=obs.spans.new_trace())
        svc.batcher._dispatch(bucket, [req])
        with pytest.raises(DeadlineExpired):
            req.future.result(timeout=0)
        expiries = obs.events.events(kind="deadline_expired")
        assert len(expiries) == 1
        assert expiries[0]["trace_id"] == req.trace_id

    def test_service_http_endpoint(self):
        from porqua_tpu.serve import BucketLadder, SolveService

        params = SolverParams(max_iter=200, polish=False)
        svc = SolveService(params=params,
                           ladder=BucketLadder((8, 16), (4, 8)),
                           max_batch=4)
        with svc:
            port = svc.start_http(0)
            svc.solve(make_qp(seed=11), timeout=120)
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            assert "porqua_serve_completed 1" in text
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10).read())
            assert health["ok"] is True and health["degraded"] is False
        # stop() took the endpoint down with the service.
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2)


# ---------------------------------------------------------------------------
# report rendering
# ---------------------------------------------------------------------------

class TestReport:
    def test_sparkline_log_scale(self):
        line = sparkline([1.0, 1e-2, 1e-4, 1e-6], log=True)
        assert len(line) == 4
        assert line[0] == "█" and line[-1] == "▁"
        assert sparkline([]) == ""
        assert sparkline([5.0, 5.0]) == "▁▁"

    def test_render_report_sections(self):
        rec = SpanRecorder()
        tid = rec.new_trace()
        for name, a, b in (("submit", 0.0, 0.1), ("queue_wait", 0.1, 0.4),
                           ("solve", 0.4, 0.9), ("resolve", 0.9, 1.0)):
            rec.record(name, a, b, trace_id=tid)
        agg = span_aggregate(rec.chrome_trace())
        assert agg["queue_wait"]["total_ms"] == pytest.approx(300.0)
        events = [
            {"t": 0, "kind": "convergence_ring", "severity": "info",
             "iters_final": 50, "iters": [25, 50],
             "prim_res": [1e-2, 1e-6], "dual_res": [1e-3, 1e-7],
             "rho": [0.1, 0.2]},
            {"t": 0, "kind": "breaker_open", "severity": "error",
             "primary": "tpu:0"},
        ]
        snapshot = {"completed": 1, "latency_p50_ms": 1.0,
                    "queue_wait_seconds": 0.3, "compiles": 0}
        text = render_report(trace=rec.chrome_trace(), events=events,
                             snapshot=snapshot)
        for needle in ("stage waterfall", "span coverage",
                       "convergence rings", "breaker_open",
                       "latency / throughput"):
            assert needle in text
