"""Contracts for the restarted-PDHG backend (``SolverParams(method="pdhg")``).

Pins what the routing subsystem stands on:

* the steppable PDHG API (``pdhg_init`` / ``pdhg_segment_step``) is
  bit-identical to the fused ``pdhg_solve`` while_loop (same compiled
  segment program — the compaction/continuous hoist cannot drift);
* solutions agree with the ADMM backend on the same problems (shared
  KKT residual measure, shared finalize), so a routing flip changes
  wall-clock, never answers;
* the restart machinery actually fires and is observable through the
  convergence rings (third slot = cumulative restart count where ADMM
  records rho);
* MAX_ITER retirement + active-set polish fallback work for PDHG lanes
  exactly as for ADMM lanes;
* the backend-agnostic drivers (vmapped batch solve, compacting
  driver) accept ``method="pdhg"`` and agree lane-for-lane.

The test family is exposure-banded mean-variance QPs (dense factor P,
budget row + signed exposure bands) — the production family whose
general rows are PDHG's winning regime — small enough for CPU CI.
"""

import dataclasses
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from porqua_tpu.compaction import CompactingDriver
from porqua_tpu.obs.rings import ring_history
from porqua_tpu.qp.admm import Status
from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.pdhg import pdhg_init, pdhg_segment_step, pdhg_solve
from porqua_tpu.qp.ruiz import equilibrate
from porqua_tpu.qp.solve import SolverParams, solve_qp, solve_qp_batch

# Moderate eps: PDHG converges in a few hundred iterations on this
# family; tight enough that the adaptive restart fires several times.
PARAMS = SolverParams(method="pdhg", max_iter=2000, eps_abs=1e-5,
                      eps_rel=1e-5, polish=False, check_interval=25)

N, M, B = 16, 5, 6


def _exposure_qp(rng, n=N, m=M, box=0.4):
    """Dense factor-model P, budget row + signed exposure bands — the
    loadgen ``build_exposure_requests`` family at test size."""
    F = rng.standard_normal((max(2, n // 4), n))
    P = F.T @ F / n + 0.1 * np.eye(n)
    C = np.concatenate([np.ones((1, n)),
                        rng.standard_normal((m - 1, n))])
    l = np.concatenate([[1.0], np.full(m - 1, -1.0)])
    u = np.ones(m)
    return CanonicalQP.build(
        P, rng.standard_normal(n) * 0.1, C=C, l=l, u=u,
        lb=np.zeros(n), ub=np.full(n, box))


def _make_batch():
    rng = np.random.default_rng(7)
    return stack_qps([_exposure_qp(rng) for _ in range(B)])


@pytest.fixture(scope="module")
def batch():
    return _make_batch()


# ---------------------------------------------------------------------------
# steppable API
# ---------------------------------------------------------------------------

def test_segment_step_matches_pdhg_solve(batch):
    """A host loop over jitted pdhg_segment_step reproduces the fused
    while_loop bit-for-bit (the twin of the ADMM stepper contract in
    test_compaction.py — same hoisted segment program)."""
    qp = jax.tree.map(lambda a: a[0], batch)
    scaled, scaling = equilibrate(qp, iters=PARAMS.scaling_iters)

    @functools.partial(jax.jit, static_argnames=("params",))
    def step(carry, s, sc, params):
        return pdhg_segment_step(carry, s, sc, params)[0]

    @functools.partial(jax.jit, static_argnames=("params",))
    def fused_solve(s, sc, params):
        return pdhg_solve(s, sc, params)

    carry = jax.jit(lambda q: pdhg_init(q, PARAMS))(scaled)
    n_segments = 0
    while (int(carry.state.status) == Status.RUNNING
           and int(carry.state.iters) < PARAMS.max_iter):
        carry = step(carry, scaled, scaling, PARAMS)
        n_segments += 1
    assert n_segments >= 2, "family must take multiple segments"
    ref = fused_solve(scaled, scaling, PARAMS)
    got = carry.state._replace(status=jnp.where(
        carry.state.status == Status.RUNNING, Status.MAX_ITER,
        carry.state.status).astype(jnp.int32))
    for name in ("x", "z", "w", "y", "mu", "rho_bar", "iters", "status",
                 "prim_res", "dual_res"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(ref, name)), err_msg=name)


def test_segment_step_never_retires_max_iter(batch):
    """The stepper leaves budget enforcement to the orchestrator: a
    lane past ``max_iter`` keeps status RUNNING until a driver (or the
    fused solve's exit) retires it."""
    qp = jax.tree.map(lambda a: a[0], batch)
    short = dataclasses.replace(PARAMS, max_iter=25)
    scaled, scaling = equilibrate(qp, iters=short.scaling_iters)
    carry = jax.jit(lambda q: pdhg_init(q, short))(scaled)

    @functools.partial(jax.jit, static_argnames=("params",))
    def step(c, s, sc, params):
        return pdhg_segment_step(c, s, sc, params)[0]

    for _ in range(3):  # 3 segments = 75 iters >> max_iter=25
        carry = step(carry, scaled, scaling, short)
    assert int(carry.state.iters) == 75
    assert int(carry.state.status) == Status.RUNNING


# ---------------------------------------------------------------------------
# solution agreement with the ADMM backend
# ---------------------------------------------------------------------------

def test_pdhg_agrees_with_admm(batch):
    """Both backends certify SOLVED on every lane and land on the same
    optimum (shared residual measure -> comparable certificates; the
    routing flip must never change answers)."""
    admm_params = dataclasses.replace(PARAMS, method="admm")
    sol_p = solve_qp_batch(batch, PARAMS)
    sol_a = solve_qp_batch(batch, admm_params)
    assert np.all(np.asarray(sol_p.status) == Status.SOLVED), (
        np.asarray(sol_p.status))
    assert np.all(np.asarray(sol_a.status) == Status.SOLVED)
    x_p, x_a = np.asarray(sol_p.x), np.asarray(sol_a.x)
    np.testing.assert_allclose(x_p, x_a, atol=2e-3)
    obj_p, obj_a = np.asarray(sol_p.obj_val), np.asarray(sol_a.obj_val)
    np.testing.assert_allclose(obj_p, obj_a, rtol=1e-3, atol=1e-5)
    # Certificates are real KKT residuals for this backend too.
    assert float(np.max(np.asarray(sol_p.prim_res))) < 1e-3
    assert float(np.max(np.asarray(sol_p.dual_res))) < 1e-3


def test_unknown_method_fails_loudly(batch):
    with pytest.raises(ValueError, match="unknown method"):
        solve_qp_batch(batch, dataclasses.replace(PARAMS, method="qpth"))


# ---------------------------------------------------------------------------
# restarts + rings
# ---------------------------------------------------------------------------

def test_restarts_fire_and_ring_records_them(batch):
    """The adaptive restart actually triggers on this family, and the
    rings' third slot carries the cumulative restart count (decoded
    chronologically it is non-decreasing and ends at the carry's
    total) — the trajectory diagnostic obs/rings exposes."""
    qp = jax.tree.map(lambda a: a[0], batch)
    ringed = dataclasses.replace(PARAMS, ring_size=64)
    scaled, scaling = equilibrate(qp, iters=ringed.scaling_iters)
    carry = jax.jit(lambda q: pdhg_init(q, ringed))(scaled)

    @functools.partial(jax.jit, static_argnames=("params",))
    def step(c, s, sc, params):
        return pdhg_segment_step(c, s, sc, params)[0]

    while (int(carry.state.status) == Status.RUNNING
           and int(carry.state.iters) < ringed.max_iter):
        carry = step(carry, scaled, scaling, ringed)

    n_restarts = int(carry.restart_count)
    assert n_restarts >= 1, "restart machinery never fired"
    hist = ring_history(carry.state.ring_prim, carry.state.ring_dual,
                        carry.state.ring_rho, int(carry.state.iters),
                        ringed.check_interval)
    counts = hist["rho"]  # PDHG: cumulative restart count per segment
    assert counts == sorted(counts), counts
    assert int(counts[-1]) == n_restarts, (counts, n_restarts)
    # The trajectory converged: final ring sample equals the state's
    # residuals exactly (polish=False contract from qp/solve.py).
    assert hist["prim_res"][-1] == float(carry.state.prim_res)
    assert hist["dual_res"][-1] == float(carry.state.dual_res)


# ---------------------------------------------------------------------------
# MAX_ITER retirement + polish fallback
# ---------------------------------------------------------------------------

def test_max_iter_polish_fallback(batch):
    """A PDHG lane retired out of budget still gets the active-set
    polish and is re-graded SOLVED when the polished point meets
    tolerance — the same finalize contract as ADMM lanes."""
    qp = jax.tree.map(lambda a: a[0], batch)
    starved = dataclasses.replace(PARAMS, max_iter=50)
    raw = solve_qp(qp, starved)
    assert int(raw.status) == Status.MAX_ITER
    polished = solve_qp(qp, dataclasses.replace(starved, polish=True))
    assert int(polished.iters) == 50  # polish adds accuracy, not iters
    assert float(polished.prim_res) <= float(raw.prim_res)
    assert float(polished.dual_res) <= float(raw.dual_res)
    # On this well-conditioned family one polish pass reaches
    # tolerance from 50 PDHG iterations -> the re-grade fires.
    assert int(polished.status) == Status.SOLVED


# ---------------------------------------------------------------------------
# backend-agnostic drivers
# ---------------------------------------------------------------------------

def test_compaction_parity_with_pdhg(batch):
    """The compacting driver is backend-agnostic: with method="pdhg"
    converged lanes are bit-identical to the vmapped fused solve, in
    the original lane order, with zero post-prewarm compiles."""
    fused = solve_qp_batch(batch, PARAMS)
    driver = CompactingDriver(PARAMS)
    compiled = driver.prewarm(B, N, M)
    assert compiled > 0
    sol, rep = driver.solve(batch)
    assert rep.compiles == 0, "prewarmed solve must not compile"
    status = np.asarray(fused.status)
    assert np.all(status == Status.SOLVED)
    np.testing.assert_array_equal(np.asarray(sol.status), status)
    for name in ("x", "z", "y", "mu", "iters", "prim_res", "dual_res"):
        np.testing.assert_array_equal(
            np.asarray(getattr(sol, name)),
            np.asarray(getattr(fused, name)), err_msg=name)
