"""Native L1-prox path vs the reference-style lifted formulation.

The reference rewrites a turnover transaction-cost term by doubling the
variable space (reference ``qp_problems.py:120-157``; mirrored by
``porqua_tpu.qp.lift.lift_turnover_objective``). The native path keeps
the problem at n variables and handles the L1 term in the ADMM w-block
prox (clipped shifted soft-threshold). Both must agree on the optimum.
"""

import numpy as np
import jax.numpy as jnp

from porqua_tpu.qp import lift
from porqua_tpu.qp.admm import SolverParams
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.solve import solve_qp


TIGHT = SolverParams(eps_abs=1e-8, eps_rel=1e-8, max_iter=20000)


def _tracking_parts(rng, n=12, T=80, tc=0.002):
    X = rng.standard_normal((T, n)) * 0.01
    w_true = rng.dirichlet(np.ones(n))
    y = X @ w_true + rng.standard_normal(T) * 0.001
    P = 2.0 * X.T @ X
    q = -2.0 * X.T @ y
    C = np.ones((1, n))
    l = u = np.ones(1)
    lb, ub = np.zeros(n), np.ones(n)
    x0 = np.full(n, 1.0 / n)
    return P, q, C, l, u, lb, ub, x0, tc


class TestL1ProxParity:
    def test_matches_lifted_formulation(self, rng):
        P, q, C, l, u, lb, ub, x0, tc = _tracking_parts(rng)
        n = len(q)

        parts = lift._as_parts(P, q, C, l, u, lb, ub)
        lifted = lift.lift_turnover_objective(parts, x0, tc)
        qp_lift = CanonicalQP.build(
            lifted["P"], lifted["q"], lifted["C"], lifted["l"], lifted["u"],
            lifted["lb"], lifted["ub"], dtype=np.float64,
        )
        sol_lift = solve_qp(qp_lift, TIGHT)
        assert bool(sol_lift.found)

        qp = CanonicalQP.build(P, q, C, l, u, lb, ub, dtype=np.float64)
        sol_prox = solve_qp(
            qp, TIGHT,
            l1_weight=jnp.full(n, tc, jnp.float64),
            l1_center=jnp.asarray(x0),
        )
        assert bool(sol_prox.found)

        np.testing.assert_allclose(
            np.asarray(sol_prox.x), np.asarray(sol_lift.x)[:n], atol=2e-5
        )
        # Total objective (quadratic + tc * |w - x0|_1) must agree.
        obj_lift = float(sol_lift.obj_val)
        obj_prox = float(sol_prox.obj_val)
        np.testing.assert_allclose(obj_prox, obj_lift, rtol=1e-5, atol=1e-9)

    def test_cost_term_reduces_turnover(self, rng):
        P, q, C, l, u, lb, ub, x0, _ = _tracking_parts(rng)
        n = len(q)
        qp = CanonicalQP.build(P, q, C, l, u, lb, ub, dtype=np.float64)

        free = solve_qp(qp, TIGHT)
        costly = solve_qp(
            qp, TIGHT,
            l1_weight=jnp.full(n, 0.05, jnp.float64),
            l1_center=jnp.asarray(x0),
        )
        to_free = float(np.abs(np.asarray(free.x) - x0).sum())
        to_cost = float(np.abs(np.asarray(costly.x) - x0).sum())
        assert to_cost < to_free
        # A large enough cost pins the portfolio at x0.
        pinned = solve_qp(
            qp, TIGHT,
            l1_weight=jnp.full(n, 10.0, jnp.float64),
            l1_center=jnp.asarray(x0),
        )
        np.testing.assert_allclose(np.asarray(pinned.x), x0, atol=1e-5)

    def test_pallas_backend_parity(self, rng):
        P, q, C, l, u, lb, ub, x0, tc = _tracking_parts(rng)
        n = len(q)
        qp = CanonicalQP.build(P, q, C, l, u, lb, ub, dtype=np.float64)
        kw = dict(l1_weight=jnp.full(n, tc, jnp.float64),
                  l1_center=jnp.asarray(x0))
        ref = solve_qp(qp, SolverParams(backend="xla"), **kw)
        pal = solve_qp(qp, SolverParams(backend="pallas"), **kw)
        assert bool(pal.found)
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=1e-5
        )


class TestMixedBatch:
    def test_zero_l1_rows_still_polished(self, rng):
        """A batch mixing costly and cost-free dates must polish the
        cost-free ones (per-problem gating, not batch-wide)."""
        from porqua_tpu.qp.canonical import stack_qps
        from porqua_tpu.qp.solve import solve_qp_batch

        P, q, C, l, u, lb, ub, x0, tc = _tracking_parts(rng)
        n = len(q)
        qp = CanonicalQP.build(P, q, C, l, u, lb, ub, dtype=np.float64)
        batch = stack_qps([qp, qp])
        l1w = jnp.stack([jnp.zeros(n, jnp.float64),
                         jnp.full(n, tc, jnp.float64)])
        l1c = jnp.stack([jnp.zeros(n, jnp.float64), jnp.asarray(x0)])

        sols = solve_qp_batch(batch, TIGHT, l1_weight=l1w, l1_center=l1c)
        plain = solve_qp(qp, TIGHT)
        prox = solve_qp(qp, TIGHT,
                        l1_weight=l1w[1], l1_center=l1c[1])
        np.testing.assert_allclose(
            np.asarray(sols.x[0]), np.asarray(plain.x), atol=1e-7
        )
        np.testing.assert_allclose(
            np.asarray(sols.x[1]), np.asarray(prox.x), atol=1e-7
        )


class TestOptimizationL1Native:
    def test_end_to_end_opt_layer(self, rng):
        """LeastSquares with transaction_cost: l1_native matches lifted."""
        import pandas as pd

        from porqua_tpu.constraints import Constraints
        from porqua_tpu.optimization import LeastSquares
        from porqua_tpu.optimization_data import OptimizationData

        n, T = 8, 100
        dates = pd.bdate_range("2021-01-01", periods=T)
        cols = [f"A{i}" for i in range(n)]
        X = pd.DataFrame(rng.standard_normal((T, n)) * 0.01,
                         index=dates, columns=cols)
        y = pd.DataFrame(
            {"bm": X.to_numpy() @ rng.dirichlet(np.ones(n))}, index=dates)
        od = OptimizationData(return_series=X, bm_series=y, align=True)
        x0 = {c: 1.0 / n for c in cols}

        weights = {}
        for native in (False, True):
            opt = LeastSquares(
                transaction_cost=0.002, x0=x0, l1_native=native,
                eps_abs=1e-8, eps_rel=1e-8, max_iter=20000,
                dtype=np.float64,
            )
            c = Constraints(selection=cols)
            c.add_budget()
            c.add_box(box_type="LongOnly", upper=1.0)
            opt.constraints = c
            opt.set_objective(od)
            assert opt.solve()
            weights[native] = np.array(
                [opt.results["weights"][a] for a in cols])

        np.testing.assert_allclose(weights[True], weights[False], atol=2e-5)

    def test_l1_native_survives_leverage_lift(self, rng):
        """A leverage constraint must not drop the native cost term
        (the lift rebuilds the parts dict; the L1 keys carry across)."""
        import pandas as pd

        from porqua_tpu.constraints import Constraints
        from porqua_tpu.optimization import LeastSquares
        from porqua_tpu.optimization_data import OptimizationData

        n, T = 8, 100
        dates = pd.bdate_range("2021-01-01", periods=T)
        cols = [f"A{i}" for i in range(n)]
        X = pd.DataFrame(rng.standard_normal((T, n)) * 0.01,
                         index=dates, columns=cols)
        y = pd.DataFrame(
            {"bm": X.to_numpy() @ rng.dirichlet(np.ones(n))}, index=dates)
        od = OptimizationData(return_series=X, bm_series=y, align=True)
        x0 = {c: 1.0 / n for c in cols}

        weights = {}
        for native in (False, True):
            opt = LeastSquares(
                transaction_cost=0.005, x0=x0, l1_native=native,
                eps_abs=1e-8, eps_rel=1e-8, max_iter=40000,
                dtype=np.float64,
            )
            c = Constraints(selection=cols)
            c.add_budget()
            c.add_box(box_type="LongShort", lower=-0.5, upper=1.0)
            c.add_l1("leverage", rhs=1.4)
            opt.constraints = c
            opt.set_objective(od)
            assert opt.solve()
            weights[native] = np.array(
                [opt.results["weights"][a] for a in cols])

        np.testing.assert_allclose(weights[True], weights[False], atol=5e-5)


def test_prox_aware_polish_l1_dual_residual(rng):
    """VERDICT item 8: cost-aware (live-L1) solves must get the same
    high-accuracy polish finish as plain ones — post-polish dual
    residual <= 1e-8 in f64, and the polish must actually help relative
    to the unpolished solve at the same iteration budget."""
    import dataclasses

    from porqua_tpu.qp.solve import Status

    n = 24
    X = rng.standard_normal((120, n)) * 0.01
    P = 2.0 * X.T @ X
    y_bm = X @ rng.dirichlet(np.ones(n))
    q = -2.0 * X.T @ y_bm
    qp = CanonicalQP.build(
        P, q, C=np.ones((1, n)), l=np.ones(1), u=np.ones(1),
        lb=np.zeros(n), ub=np.ones(n), dtype=jnp.float64,
    )
    w_prev = rng.dirichlet(np.ones(n))
    l1w = jnp.full(n, 2e-4, jnp.float64)
    l1c = jnp.asarray(w_prev)

    # A deliberately loose ADMM budget: the unpolished point stops well
    # short of 1e-8, so reaching it demonstrates the polish works on
    # live-L1 problems rather than the iteration loop doing everything.
    params = SolverParams(eps_abs=1e-3, eps_rel=1e-3, max_iter=50,
                          polish=True)
    unpolished = solve_qp(
        qp, dataclasses.replace(params, polish=False),
        l1_weight=l1w, l1_center=l1c)
    assert float(unpolished.dual_res) > 1e-8

    sol = solve_qp(qp, params, l1_weight=l1w, l1_center=l1c)
    assert int(sol.status) == Status.SOLVED
    assert float(sol.dual_res) <= 1e-8, float(sol.dual_res)
    assert float(sol.prim_res) <= 1e-8, float(sol.prim_res)
    assert float(sol.dual_res) < float(unpolished.dual_res)

    # The polished point must still be the L1 optimum: match the lifted
    # 2n formulation solved tight.
    from porqua_tpu.qp import lift

    parts = lift._as_parts(
        np.asarray(P), np.asarray(q), np.ones((1, n)), np.ones(1),
        np.ones(1), np.zeros(n), np.ones(n))
    lifted = lift.lift_turnover_objective(parts, w_prev, 2e-4)
    qp_l = CanonicalQP.build(
        lifted["P"], lifted["q"], C=lifted["C"], l=lifted["l"],
        u=lifted["u"], lb=lifted["lb"], ub=lifted["ub"],
        dtype=jnp.float64)
    sol_l = solve_qp(qp_l, SolverParams(
        eps_abs=1e-9, eps_rel=1e-9, max_iter=20000, polish=True))
    np.testing.assert_allclose(
        np.asarray(sol.x), np.asarray(sol_l.x)[:n], atol=5e-7)


def test_l1_duality_gap_valid(rng):
    """ADVICE: with a native L1 term the reported duality gap must be a
    real weak-duality bound (split the combined box dual into its L1
    subgradient and box parts), not the plain-QP formula fed an invalid
    dual. At a tightly solved point the gap must be ~0."""
    P, q, C, l, u, lb, ub, x0, tc = _tracking_parts(rng)
    n = len(q)
    qp = CanonicalQP.build(P, q, C, l, u, lb, ub, dtype=np.float64)
    sol = solve_qp(
        qp, TIGHT,
        l1_weight=jnp.full(n, tc, jnp.float64),
        l1_center=jnp.asarray(x0),
    )
    assert bool(sol.found)
    assert float(sol.duality_gap) < 1e-7, float(sol.duality_gap)

    # And on an interior-kink solution (huge cost pins x at x0, where
    # the subgradient is strictly inside [-w, w]) the gap must still be
    # finite and tiny.
    pinned = solve_qp(
        qp, TIGHT,
        l1_weight=jnp.full(n, 10.0, jnp.float64),
        l1_center=jnp.asarray(x0),
    )
    assert np.isfinite(float(pinned.duality_gap))
    assert float(pinned.duality_gap) < 1e-6, float(pinned.duality_gap)
