"""Device-truth profiling plane: CostRecord warehouse, measured
roofline, fusion-target attribution, and the gate rules (ISSUE 12
acceptance)."""

import json
import os
import sys
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from porqua_tpu.obs import HarvestSink
from porqua_tpu.obs.devprof import (
    CostLog,
    cost_record,
    executable_cost,
    executable_memory,
    hlo_fingerprint,
    load_cost_records,
    roofline_verdict,
    write_cost_records,
)
from porqua_tpu.obs.profile import qp_solve_profile
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.solve import (
    SolverParams,
    aot_compile_batch,
    batch_shape_struct,
)
from porqua_tpu.serve.bucketing import Bucket, ExecutableCache

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def make_qp(n=6, m=2, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * n, n))
    P = A.T @ A / (2 * n) + np.eye(n)
    q = rng.standard_normal(n)
    C = np.concatenate([np.ones((1, n)), rng.standard_normal((m - 1, n))])
    return CanonicalQP.build(
        P, q, C=C, l=np.full(m, -1.0), u=np.ones(m),
        lb=np.zeros(n), ub=np.ones(n), dtype=dtype)


# ---------------------------------------------------------------------------
# CostRecord schema + warehouse
# ---------------------------------------------------------------------------

class TestCostRecord:
    def test_harvest_from_real_executable(self):
        """A real compiled program yields real XLA numbers: flops and
        bytes from cost_analysis, memory classes from memory_analysis,
        and a stable HLO fingerprint."""
        struct = jax.ShapeDtypeStruct((16, 16), jnp.float32)
        compiled = jax.jit(lambda a: a @ a + 1.0).lower(struct).compile()
        rec = cost_record(compiled, entry="probe", kind="test",
                          bucket="16x16", slots=1, dtype="<f4",
                          device="cpu:0", compile_s=0.5)
        assert rec["v"] == 1 and rec["entry"] == "probe"
        # One 16x16x16 matmul = 2*16^3 = 8192 flops, plus the add.
        assert rec["flops"] >= 2 * 16 ** 3
        assert rec["bytes_accessed"] > 0
        assert rec["argument_bytes"] == 16 * 16 * 4
        assert rec["output_bytes"] == 16 * 16 * 4
        assert rec["peak_bytes"] > 0
        assert len(rec["hlo_hash"]) == 16
        # The fingerprint is a program identity: recompiling the SAME
        # program reproduces it; a different program changes it.
        again = jax.jit(lambda a: a @ a + 1.0).lower(struct).compile()
        assert hlo_fingerprint(again) == rec["hlo_hash"]
        other = jax.jit(lambda a: a @ a + 2.0).lower(struct).compile()
        assert hlo_fingerprint(other) != rec["hlo_hash"]

    def test_analysis_refusal_never_raises(self):
        """A backend/object that refuses every analysis yields None
        fields, not an exception — the compile path must not care."""
        class Refuses:
            def cost_analysis(self):
                raise NotImplementedError

            def memory_analysis(self):
                raise NotImplementedError

            def as_text(self):
                raise NotImplementedError

        assert executable_cost(Refuses()) == {"flops": None,
                                              "bytes_accessed": None}
        assert executable_memory(Refuses()) == {"peak_bytes": None}
        rec = cost_record(Refuses(), entry="x", kind="y")
        assert rec["flops"] is None and rec["hlo_hash"] is None

    def test_jsonl_roundtrip(self, tmp_path):
        rec = {"v": 1, "kind": "solve", "entry": "solve",
               "bucket": "8x4", "slots": 2, "flops": 123.0,
               "bytes_accessed": 456.0, "peak_bytes": 789.0}
        for name in ("c.jsonl", "c.jsonl.gz"):
            path = str(tmp_path / name)
            with CostLog(path) as log:
                log.emit(rec)
                log.emit(dict(rec, slots=4))
                assert log.records == 2 and log.write_failures == 0
            back = load_cost_records(path)
            assert len(back) == 2
            assert back[0]["flops"] == 123.0 and back[1]["slots"] == 4

    def test_dead_log_degrades_to_counters(self, tmp_path):
        log = CostLog(str(tmp_path / "nodir" / "c.jsonl"))
        assert log.write_failures == 1
        log.emit({"v": 1})
        assert log.records == 1  # counted, not raised
        mem = CostLog()
        mem.emit({"v": 1, "entry": "a"})
        assert mem.buffered()[0]["entry"] == "a"
        assert mem.counters() == {"cost_records": 1,
                                  "cost_write_failures": 0}


# ---------------------------------------------------------------------------
# ExecutableCache harvesting + per-bucket exposition
# ---------------------------------------------------------------------------

class TestCacheHarvest:
    def test_solve_and_continuous_entries_harvested(self, tmp_path):
        path = str(tmp_path / "costs.jsonl")
        params = SolverParams(max_iter=100, polish=False)
        cache = ExecutableCache(params, cost_log=CostLog(path))
        b = Bucket(8, 4)
        cache.get(b, 2, np.float32)
        cache.get_continuous(b, 2, np.float32)
        cache.cost_log.close()
        recs = load_cost_records(path)
        # One record for the one-shot solve, three for the triple.
        assert sorted((r["kind"], r["entry"]) for r in recs) == [
            ("continuous", "admit"), ("continuous", "finalize"),
            ("continuous", "step"), ("solve", "solve")]
        for rec in recs:
            assert rec["bucket"] == "8x4" and rec["slots"] == 2
            assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
            assert rec["peak_bytes"] > 0 and rec["compile_s"] > 0
        # In-process lookup sees the same records.
        assert len(cache.cost_records()) == 4
        solve_rec = cache.cost_record_for(b, 2, np.float32)
        assert solve_rec["entry"] == "solve"
        step_rec = cache.cost_record_for(b, 2, np.float32,
                                         kind="continuous")
        assert step_rec["entry"] == "step"
        assert cache.cost_record_for(Bucket(16, 4), 2, np.float32) is None

    def test_bucket_stats_and_gauges(self):
        params = SolverParams(max_iter=100, polish=False)
        cache = ExecutableCache(params)
        b = Bucket(8, 4)
        cache.get(b, 1, np.float32)
        cache.get(b, 1, np.float32)  # hit
        stats = cache.bucket_stats()["8x4"]
        assert stats["compiles"] == 1 and stats["cache_hits"] == 1
        assert stats["compile_seconds"] > 0
        assert stats["peak_bytes_max"] > 0
        gauges = cache.prometheus_gauges()
        assert gauges["bucket_compiles_total"] == [({"bucket": "8x4"}, 1)]
        assert gauges["bucket_cache_hits_total"] == [({"bucket": "8x4"}, 1)]
        ((tag, peak),) = gauges["bucket_peak_bytes"]
        assert tag == {"bucket": "8x4"} and peak > 0

    def test_disabled_mode_harvests_nothing(self):
        params = SolverParams(max_iter=100, polish=False)
        cache = ExecutableCache(params, cost_log=False)
        cache.get(Bucket(8, 4), 1, np.float32)
        assert cache.cost_log is None
        assert cache.cost_records() == []
        # Cache-health stats still accumulate (they predate the plane).
        assert cache.bucket_stats()["8x4"]["compiles"] == 1

    def test_metrics_endpoint_carries_bucket_gauges(self):
        from porqua_tpu.serve import BucketLadder, SolveService

        params = SolverParams(max_iter=200, polish=False)
        svc = SolveService(params=params,
                           ladder=BucketLadder((8, 16), (4, 8)),
                           max_batch=4)
        with svc:
            port = svc.start_http(0)
            svc.solve(make_qp(seed=7), timeout=120)
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            assert "# TYPE porqua_serve_bucket_compile_seconds_total " \
                   "gauge" in text
            assert 'porqua_serve_bucket_compiles_total{bucket="8x4"}' \
                in text
            assert 'porqua_serve_bucket_peak_bytes{bucket="8x4"}' in text
            assert "porqua_serve_cost_records" in text
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10).read())
            assert health["cache"]["executables"] >= 1
            bstats = health["cache"]["buckets"]["8x4"]
            assert bstats["compiles"] >= 1
            assert bstats["peak_bytes_max"] > 0
            assert health["cost_records"] >= 1


# ---------------------------------------------------------------------------
# measured-vs-model reconciliation + identity pins
# ---------------------------------------------------------------------------

class TestMeasuredProfile:
    def test_profile_switches_numerators_to_xla(self):
        """On a known shape, a profile handed the executable's own
        CostRecord reports XLA numerators with the analytic model side
        by side — and the two agree on order of magnitude (the model
        mirrors the real program; a 10x disagreement would mean one of
        them is counting a different algorithm)."""
        params = SolverParams(max_iter=100, polish=False)
        B, n, m = 4, 16, 4
        struct = batch_shape_struct(B, n, m)
        compiled = aot_compile_batch(struct, params)
        rec = cost_record(compiled, entry="solve", kind="solve",
                          bucket=f"{n}x{m}", slots=B, dtype="<f4")
        assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
        prof = qp_solve_profile(n, m, 50.0, 0.01, params=params,
                                batch=B, cost=rec)
        assert prof["cost_source"] == "xla"
        assert prof["flops_est"] == rec["flops"]
        assert prof["bytes_est"] == rec["bytes_accessed"]
        assert prof["peak_bytes"] == rec["peak_bytes"]
        assert prof["model_flops"] > 0 and prof["model_bytes"] > 0
        # Achieved rates use the XLA numerators.
        assert prof["achieved_tflops"] == pytest.approx(
            rec["flops"] / 0.01 / 1e12)
        # Drift is tracked, and bounded: model and compiler count the
        # same program within two orders of magnitude on this tiny
        # shape (XLA counts a full while_loop trip budget; the model
        # counts executed iterations — the ratio is the tracked drift,
        # not a hidden constant).
        assert prof["flops_model_ratio"] > 0
        assert prof["bytes_model_ratio"] > 0

    def test_profile_without_cost_is_unchanged(self):
        p = SolverParams(polish=False)
        prof = qp_solve_profile(16, 4, 50.0, 0.01, params=p)
        assert prof["cost_source"] == "model"
        assert "flops_xla" not in prof and "model_flops" not in prof
        assert prof["flops_est"] > 0 and prof["achieved_tflops"] > 0

    def test_empty_cost_record_falls_back_to_model(self):
        p = SolverParams(polish=False)
        prof = qp_solve_profile(16, 4, 50.0, 0.01, params=p,
                                cost={"flops": None,
                                      "bytes_accessed": None})
        assert prof["cost_source"] == "model"
        assert prof["flops_est"] > 0

    def test_gc107_devprof_identity_clean(self):
        from porqua_tpu.analysis import contracts

        assert contracts.check_devprof_identity() == []

    def test_disabled_is_bit_identical(self):
        """The acceptance pin: a service whose cache harvests cost
        records returns byte-for-byte the arrays one with the plane
        disabled does (harvesting reads compiled objects post-build;
        the jaxpr half is contract GC107)."""
        from porqua_tpu.serve import BucketLadder, SolveService

        params = SolverParams(max_iter=300, polish=False)
        qp = make_qp(seed=3)
        results = []
        for cost_log in (False, None):
            svc = SolveService(params=params,
                               ladder=BucketLadder((8, 16), (4, 8)),
                               max_batch=4, warm_start=False,
                               cost_log=cost_log)
            with svc:
                results.append(svc.solve(qp, timeout=120))
        off, on = results
        np.testing.assert_array_equal(np.asarray(off.x), np.asarray(on.x))
        np.testing.assert_array_equal(np.asarray(off.iters),
                                      np.asarray(on.iters))


# ---------------------------------------------------------------------------
# loadgen export + serve harvest records carry measured profiles
# ---------------------------------------------------------------------------

class TestLoadgenCostOut:
    def test_cost_out_and_measured_harvest_profiles(self, tmp_path):
        from porqua_tpu.serve.loadgen import (
            build_tracking_requests, run_loadgen)

        cost_path = str(tmp_path / "costs.jsonl")
        harvest_path = str(tmp_path / "harvest.jsonl")
        requests = build_tracking_requests(16, n_assets=8, window=32)
        report = run_loadgen(requests, max_batch=8,
                             harvest_out=harvest_path,
                             cost_out=cost_path)
        assert report["errors"] == 0
        assert report["cost_out"] == cost_path
        assert report["cost_records"] >= 1
        summary = report["cost_summary"]
        assert summary["executables"] == report["cost_records"]
        assert summary["bytes_accessed_max"] > 0
        assert summary["peak_bytes_max"] > 0
        recs = load_cost_records(cost_path)
        assert len(recs) == report["cost_records"]
        assert all(r["kind"] == "solve" for r in recs)
        # The serve harvest records switched their profile numerators
        # to the executable's own cost analysis.
        from porqua_tpu.obs import load_harvest

        solves = load_harvest(harvest_path)
        assert solves
        for rec in solves:
            prof = rec["profile"]
            assert prof["cost_source"] == "xla"
            assert prof["flops_xla"] > 0 and prof["bytes_xla"] > 0
            assert prof["model_flops"] > 0
            assert prof["peak_bytes"] > 0


class TestFlightCostAttach:
    def test_bundle_carries_implicated_bucket_costs(self):
        from porqua_tpu.obs.flight import FlightRecorder

        params = SolverParams(max_iter=100, polish=False)
        cache = ExecutableCache(params)
        cache.get(Bucket(8, 4), 1, np.float32)
        cache.get(Bucket(16, 4), 1, np.float32)
        flight = FlightRecorder(out_dir=None, debounce_s=0.0)
        flight.attach(cache=cache)
        bundle = flight.dump("dispatch_failure", bucket="8x4")
        assert bundle["implicated_bucket"] == "8x4"
        assert bundle["cost_records"]
        assert all(r["bucket"] == "8x4" for r in bundle["cost_records"])
        # A trigger naming no bucket gets the whole harvested set.
        bundle2 = flight.dump("manual")
        assert len(bundle2["cost_records"]) == 2


# ---------------------------------------------------------------------------
# roofline verdict + gate rules
# ---------------------------------------------------------------------------

class TestRooflineVerdict:
    def test_ranks_by_measured_bytes_and_joins_stages(self):
        recs = [
            {"kind": "continuous", "entry": "step", "bucket": "512x8",
             "slots": 64, "dtype": "<f4", "device": "tpu:0",
             "flops": 1e9, "bytes_accessed": 4e9, "peak_bytes": 1e9},
            {"kind": "solve", "entry": "solve", "bucket": "32x8",
             "slots": 8, "dtype": "<f4", "device": "tpu:0",
             "flops": 1e7, "bytes_accessed": 2e7, "peak_bytes": 1e7},
        ]
        v = roofline_verdict(
            recs, stage_seconds={"serve/segment_step": 1.5},
            top=1, device_kind="TPU v5 lite")
        assert v["executables"] == 2
        assert v["ranked"][0]["entry"] == "step"
        assert v["ranked"][0]["bound"] == "memory"
        assert v["ranked"][0]["stage_seconds"] == {
            "serve/segment_step": 1.5}
        assert len(v["fusion_candidates"]) == 1
        assert v["fusion_candidates"][0]["entry"] == "step"
        assert "top fusion target: step" in v["verdict"]

    def test_verdict_from_real_cache(self, tmp_path):
        """End to end: compile through the real cache, export, verdict
        — the acceptance path `bench/loadgen -> CostLog ->
        roofline_report` without a synthetic record in sight."""
        params = SolverParams(max_iter=100, polish=False)
        cache = ExecutableCache(params)
        cache.prewarm(Bucket(8, 4), 2, np.float32)
        path = str(tmp_path / "c.jsonl")
        write_cost_records(path, cache.cost_records())
        v = roofline_verdict(load_cost_records(path), top=2)
        assert v["executables"] == 2
        assert v["fusion_candidates"]
        assert v["ranked"][0]["bytes_accessed"] > 0

    def test_selftest_passes(self):
        sys.path.insert(0, _SCRIPTS)
        try:
            import roofline_report
        finally:
            sys.path.remove(_SCRIPTS)
        assert roofline_report._selftest() == 0


class TestGateCostRules:
    @pytest.fixture()
    def gate(self):
        sys.path.insert(0, _SCRIPTS)
        try:
            import bench_gate
        finally:
            sys.path.remove(_SCRIPTS)
        return bench_gate

    def test_cost_drift_cells(self, gate):
        base = gate._synthetic_baseline()
        # Pass: identical cost numbers.
        good = json.loads(json.dumps(base))
        assert gate.check_payload(base, good)["ok"]
        # Fail: flops drifted past the band (program changed).
        bad = json.loads(json.dumps(base))
        bad["xla_cost"]["flops"] *= 1.25
        v = gate.check_payload(base, bad)
        assert not v["ok"] and "xla_flops_drift" in v["failed"]
        # Fail: serving peak memory grew past the band.
        bad2 = json.loads(json.dumps(base))
        bad2["config_serving"]["cost_summary"]["peak_bytes_max"] *= 1.3
        v2 = gate.check_payload(base, bad2)
        assert not v2["ok"] and "serving_peak_memory" in v2["failed"]
        # Pass: peak memory SHRANK (one-sided rule).
        better = json.loads(json.dumps(base))
        better["xla_cost"]["peak_bytes"] *= 0.7
        assert gate.check_payload(base, better)["ok"]
        # Old baselines without xla_cost skip, not fail.
        old = {k: v for k, v in base.items() if k != "xla_cost"}
        old["config_serving"] = {
            k: v for k, v in base["config_serving"].items()
            if k != "cost_summary"}
        v3 = gate.check_payload(old, good)
        assert v3["ok"] and v3["n_skip"] >= 3
        # A candidate that LOST the cost coverage fails (coverage
        # regressions count — same posture as every other metric).
        lossy = {k: v for k, v in good.items() if k != "xla_cost"}
        v4 = gate.check_payload(base, lossy)
        assert not v4["ok"] and "xla_flops_drift" in v4["failed"]

    def test_selftest_covers_cost_rules(self, gate):
        assert gate._selftest() == 0
