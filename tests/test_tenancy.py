"""Tenancy plane tests: quotas, DRR fair share, per-tenant metrics/
SLOs, harvest schema v2 evolution, workload blends, GC109.

The tentpole invariants (README "Multi-tenant serving & workload
library"): one tenant's burst sheds at its OWN bounded sub-queue and
cannot starve another tenant's deadline; per-tenant attribution
(counters, latency histograms, SLO engines, harvest records)
reconciles exactly; tenancy is host-side only (GC109: the tenant plane
leaves the solve/serve jaxprs string-identical); and v1 (pre-tenant)
harvest datasets — the committed ``HARVEST_r07.json`` included — keep
loading with the legacy sentinel tenant.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from porqua_tpu.obs import TenantSLOSet
from porqua_tpu.obs.anomaly import AnomalyDetector
from porqua_tpu.obs.events import EventBus
from porqua_tpu.obs.exposition import prometheus_text
from porqua_tpu.obs.harvest import (
    DEFAULT_TENANT,
    LEGACY_TENANT,
    SCHEMA_VERSION,
    HarvestSink,
    aggregate,
    load_harvest,
    solve_record,
)
from porqua_tpu.obs.slo import BurnRateRule, default_slos
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.solve import SolverParams
from porqua_tpu.resilience.faults import FaultClock
from porqua_tpu.serve import BucketLadder, QueueFull, ServeMetrics, SolveService
from porqua_tpu.serve.tenancy import FairPendingQueue, TenantAdmission

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PARAMS = SolverParams(max_iter=500, eps_abs=1e-5, eps_rel=1e-5,
                      polish=False, check_interval=25)


def _qp(seed=0, nv=6, m=2):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * nv, nv))
    P = A.T @ A / (2 * nv) + np.eye(nv)
    q = rng.standard_normal(nv)
    C = np.concatenate([np.ones((1, nv)),
                        rng.standard_normal((m - 1, nv))])
    return CanonicalQP.build(P, q, C=C, l=np.full(m, -1.0),
                             u=np.ones(m), lb=np.zeros(nv),
                             ub=np.ones(nv))


class _Req:
    def __init__(self, tenant, submitted=0.0):
        self.tenant = tenant
        self.submitted = float(submitted)


# ---------------------------------------------------------------------------
# tenancy primitives
# ---------------------------------------------------------------------------

def test_admission_quota_sheds_only_offender():
    adm = TenantAdmission(quota={"noisy": 3})
    assert all(adm.try_admit("noisy") for _ in range(3))
    assert not adm.try_admit("noisy")          # at quota: shed
    assert all(adm.try_admit("quiet") for _ in range(100))  # unbounded
    assert adm.depth("noisy") == 3
    adm.release("noisy")
    assert adm.try_admit("noisy")              # a release frees a slot
    assert adm.sheds() == {"noisy": 1}


def test_admission_cardinality_bounded_by_overflow_lane():
    """Tenant ids are caller-supplied strings: past max_tenants, new
    ids fold into one shared overflow lane so an id-spraying client
    cannot grow the scheduler dicts (or /healthz depths) without
    limit. A tenant first seen at capacity maps to the overflow lane
    on admit AND release."""
    adm = TenantAdmission(quota={"known": 4}, max_tenants=2)
    assert adm.try_admit("a") and adm.try_admit("b")
    for i in range(50):
        adm.try_admit(f"spray-{i}")
    assert set(adm.depths()) == {"a", "b", TenantAdmission.OVERFLOW}
    assert adm.depths()[TenantAdmission.OVERFLOW] == 50
    adm.release("spray-0")  # releases the overflow lane, not a new key
    assert adm.depths()[TenantAdmission.OVERFLOW] == 49
    # Explicitly-configured tenants keep their own lane regardless.
    assert adm.try_admit("known") and adm.depth("known") == 1


def test_admission_int_quota_applies_to_every_tenant():
    adm = TenantAdmission(quota=2)
    for t in ("a", "b"):
        assert adm.try_admit(t) and adm.try_admit(t)
        assert not adm.try_admit(t)
    assert adm.depths() == {"a": 2, "b": 2}


def test_drr_interleaves_burst_backlog():
    """A 10-deep burst backlog cannot starve the quiet tenant: at
    equal weights the dequeue alternates tenants 1:1."""
    fq = FairPendingQueue()
    for i in range(10):
        fq.append(_Req("noisy", i))
    fq.append(_Req("quiet", 100.0))
    order = [fq.popleft().tenant for _ in range(4)]
    assert "quiet" in order[:2], order
    # Remaining pops drain the noisy backlog.
    rest = [fq.popleft().tenant for _ in range(len(fq))]
    assert rest.count("noisy") == len(rest)
    with pytest.raises(IndexError):
        fq.popleft()


def test_drr_weights_grant_proportional_slots():
    fq = FairPendingQueue(weights={"heavy": 2.0})
    for i in range(20):
        fq.append(_Req("heavy", i))
        fq.append(_Req("light", i))
    first = [fq.popleft().tenant for _ in range(12)]
    assert first.count("heavy") >= 7, first  # ~2:1 service ratio


def test_fair_queue_peek_is_oldest_across_tenants():
    fq = FairPendingQueue()
    fq.append(_Req("b", 5.0))
    fq.append(_Req("a", 1.0))
    assert fq[0].tenant == "a" and fq.oldest_submitted() == 1.0
    assert len(fq) == 2 and bool(fq)


def test_fair_queue_releases_admission_on_every_pop():
    adm = TenantAdmission(quota=8)
    fq = FairPendingQueue(admission=adm)
    for i in range(4):
        assert adm.try_admit("t")
        fq.append(_Req("t", i))
    assert adm.depth("t") == 4
    for _ in range(4):
        fq.popleft()
    assert adm.depth("t") == 0


# ---------------------------------------------------------------------------
# per-tenant metrics + exposition
# ---------------------------------------------------------------------------

def test_tenant_metrics_counters_and_latency():
    m = ServeMetrics()
    m.inc_tenant("a", "submitted", 3)
    m.inc_tenant("a", "completed", 2)
    m.inc_tenant("b", "rejected")
    for s in (0.004, 0.008, 0.120):
        m.observe_tenant_latency("a", s)
    m.inc_tenant(None, "completed")  # no-op, no tenant
    snap = m.snapshot()["tenants"]
    assert snap["a"]["submitted"] == 3 and snap["a"]["completed"] == 2
    assert snap["b"]["rejected"] == 1
    assert snap["a"]["latency_p99_ms"] > 100.0
    assert None not in snap
    # The SLO view: sheds count as availability bad events.
    sample = m.tenant_slo_sample("b")
    assert sample["failed"] == 1 and sample["completed"] == 0
    assert m.tenant_view("b").slo_sample() == sample
    # Window reset clears the tenant axis with everything else.
    m.reset_window()
    assert "tenants" not in m.snapshot()


def test_tenant_cardinality_bounded_by_overflow_lane():
    m = ServeMetrics(max_tenants=4)
    for i in range(10):
        m.inc_tenant(f"t{i}", "submitted")
    snap = m.snapshot()["tenants"]
    assert len(snap) == 5  # 4 real + the overflow lane
    assert snap[ServeMetrics._TENANT_OVERFLOW]["submitted"] == 6


def test_prometheus_escapes_hostile_tenant_label():
    """Regression (satellite): tenant ids are caller-supplied strings;
    an unescaped backslash/quote/newline in a label VALUE invalidates
    the whole scrape per the text exposition format."""
    m = ServeMetrics()
    hostile = 'evil"tenant\\with\nnewline'
    m.inc_tenant(hostile, "completed", 2)
    text = prometheus_text(m.snapshot(),
                           labeled_gauges=m.tenant_labeled_gauges())
    line = next(ln for ln in text.splitlines()
                if ln.startswith("porqua_serve_tenant_completed{"))
    assert '\\"' in line and "\\\\" in line and "\\n" in line, line
    # The raw control characters must NOT survive into the exposition:
    # every emitted line is exactly one series.
    assert "\n" not in line and line.endswith(" 2")
    # And every value round-trips through the documented unescaping.
    label = line.split("{", 1)[1].rsplit("}", 1)[0]
    value = label.split('="', 1)[1][:-1]
    unescaped = (value.replace("\\n", "\n").replace('\\"', '"')
                 .replace("\\\\", "\\"))
    assert unescaped == hostile


# ---------------------------------------------------------------------------
# per-tenant SLO engines
# ---------------------------------------------------------------------------

def test_tenant_slo_set_fires_only_offender_with_label():
    clk = FaultClock()
    m = ServeMetrics()
    ev = EventBus()
    ts = TenantSLOSet(
        slos=default_slos(latency_target_s=5.0),
        rules=(BurnRateRule("fast", long_s=3600.0, short_s=300.0,
                            burn_rate=14.4, resolve_s=3600.0),),
        clock=clk, min_eval_interval_s=0.0).bind(m, events=ev)
    for t in ("noisy", "quiet"):
        m.inc_tenant(t, "completed")
    ts.evaluate()
    m.inc_tenant("noisy", "completed", 2)
    m.inc_tenant("noisy", "rejected", 98)   # quota sheds burn budget
    m.inc_tenant("quiet", "completed", 100)
    clk.advance(10.0)
    ts.evaluate()
    fired = ts.alerts_fired()
    assert fired["noisy"] == 1 and fired["quiet"] == 0, fired
    alerts = ev.events("slo_alert")
    assert all(e.get("tenant") == "noisy" for e in alerts), alerts
    status = ts.status()
    assert status["quiet"]["slos"]["availability"]["compliance"] == 1.0
    gauges = ts.labeled_gauges()
    assert ({"tenant": "noisy"}, 2.0) in \
        gauges["tenant_slo_alert_state_availability_fast"]
    counters = ts.counters()
    assert counters["tenant_slo_engines"] == 2
    assert counters["tenant_slo_alerts_fired"] == 1


def test_tenant_slo_set_bounds_engine_count():
    m = ServeMetrics()
    ts = TenantSLOSet(max_tenants=2, min_eval_interval_s=0.0).bind(m)
    for i in range(5):
        m.inc_tenant(f"t{i}", "completed")
    ts.evaluate()
    assert ts.counters()["tenant_slo_engines"] == 2
    assert ts.counters()["tenant_slo_overflow"] >= 3


# ---------------------------------------------------------------------------
# harvest schema evolution (satellite)
# ---------------------------------------------------------------------------

def test_schema_v2_records_carry_tenant():
    assert SCHEMA_VERSION == 2
    rec = solve_record("serve", 6, 2, 1, 10, 0.0, 0.0, 0.0)
    assert rec["v"] == 2 and rec["tenant"] == DEFAULT_TENANT
    tagged = solve_record("serve", 6, 2, 1, 10, 0.0, 0.0, 0.0,
                          tenant="fund-a")
    assert tagged["tenant"] == "fund-a"


def test_v1_records_aggregate_under_legacy_sentinel(tmp_path):
    """A v1 dataset (no tenant field) must keep loading: tenant
    defaults to the LEGACY_TENANT sentinel, distinguishable from a
    real v2 'default'-lane record."""
    path = tmp_path / "v1.jsonl"
    v1 = {"v": 1, "t": 0.0, "source": "serve", "n": 6, "m": 2,
          "status": 1, "iters": 50, "prim_res": 1e-6, "dual_res": 1e-6,
          "obj_val": -1.0, "warm": False, "bucket": "8x4",
          "eps_abs": 1e-5, "check_interval": 25, "segments": 2}
    with open(path, "w") as f:
        f.write(json.dumps(v1) + "\n")
        f.write(json.dumps(v1) + "\n")
    records = load_harvest(str(path))
    agg = aggregate(records)
    assert agg["tenants"] == {LEGACY_TENANT: 2}
    (group,) = agg["groups"]
    assert group["tenant"] == LEGACY_TENANT and group["count"] == 2


def test_committed_v1_harvest_r07_still_consumable():
    """The committed pre-tenant artifact (a schema-v1 AGGREGATE whose
    groups carry no tenant key) must keep feeding every v2 consumer:
    the harvest_report renderer and the anomaly baseline builder."""
    path = os.path.join(_REPO, "HARVEST_r07.json")
    if not os.path.exists(path):
        pytest.skip("HARVEST_r07.json not committed")
    with open(path) as f:
        agg = json.load(f)
    assert agg["schema_version"] == 1
    assert agg["groups"] and all("tenant" not in g
                                 for g in agg["groups"])
    # The report renderer consumes the v1 aggregate unchanged (the
    # tenant column renders the '-' placeholder).
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "harvest_report", os.path.join(_REPO, "scripts",
                                       "harvest_report.py"))
    hr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hr)
    text = hr.render_table(agg)
    assert f"{agg['records']} records" in text
    # The anomaly baseline builder still calibrates from it — same
    # (bucket, eps) band set the PR 8 detector shipped with.
    det = AnomalyDetector.from_aggregate(agg)
    assert len(det.baseline) == len(agg["groups"])
    # And a v1 RECORD stream re-aggregated today lands under the
    # sentinel tenant (pinned structurally in
    # test_v1_records_aggregate_under_legacy_sentinel; here against
    # the committed groups' own shape).
    v1_rec = {"v": 1, "source": "serve", "n": 24, "m": 1, "status": 1,
              "iters": int(agg["groups"][0]["iters"]["p50"]),
              "prim_res": 1e-6, "dual_res": 1e-6, "obj_val": -1.0,
              "bucket": agg["groups"][0]["bucket"],
              "eps_abs": agg["groups"][0]["eps_abs"]}
    re_agg = aggregate([v1_rec])
    assert re_agg["groups"][0]["tenant"] == LEGACY_TENANT


def test_tenant_grouping_round_trips(tmp_path):
    """Per-(tenant, bucket, eps) grouping: two tenants on the same
    (bucket, eps) keep separate rows, and the anomaly baseline merges
    them back to one conservative (bucket, eps) band."""
    path = tmp_path / "v2.jsonl.gz"
    with HarvestSink(str(path)) as sink:
        for tenant, iters in (("a", 50), ("a", 60), ("b", 200)):
            sink.emit(solve_record(
                "serve", 6, 2, 1, iters, 1e-6, 1e-6, -1.0,
                bucket="8x4", eps_abs=1e-5, check_interval=25,
                tenant=tenant))
    agg = aggregate(load_harvest(str(path)))
    keys = {(g["tenant"], g["bucket"], g["eps_abs"])
            for g in agg["groups"]}
    assert keys == {("a", "8x4", 1e-5), ("b", "8x4", 1e-5)}
    det = AnomalyDetector.from_aggregate(agg)
    assert set(det.baseline) == {("8x4", 1e-5)}
    base = det.baseline[("8x4", 1e-5)]
    assert base["count"] == 3
    assert base["iters_p95"] == 200.0  # the widest tenant's band


def test_anomaly_detector_tenant_axis():
    """Online EWMAs split per tenant against the shared baseline: one
    tenant's drift fires an event naming that tenant; the other
    tenant's group stays clean."""
    ev = EventBus()
    det = AnomalyDetector(
        {("8x4", 1e-5): {"iters_p50": 50.0, "iters_p95": 100.0,
                         "iters_max": 150.0, "wasted": 0.1,
                         "count": 64}},
        min_samples=4, events=ev)
    for _ in range(8):
        det.observe("8x4", 1e-5, iters=5000, segments=200,
                    check_interval=25, tenant="bad")
        det.observe("8x4", 1e-5, iters=50, segments=2,
                    check_interval=25, tenant="good")
    st = det.status()
    assert st["fired"] == 1
    assert st["anomalous"] == ["bad/8x4@1e-05"], st["anomalous"]
    events = ev.events("convergence_anomaly")
    assert events and events[0]["tenant"] == "bad"


# ---------------------------------------------------------------------------
# end-to-end service behavior
# ---------------------------------------------------------------------------

def test_service_quota_shed_and_attribution():
    """Live service: the offender's overflow sheds with QueueFull at
    ITS quota, counted on its own series; the victim's traffic is
    untouched; per-tenant completed == per-tenant harvest records."""
    sink = HarvestSink(None)
    service = SolveService(
        params=PARAMS, ladder=BucketLadder(n_rungs=(8,), m_rungs=(4,)),
        max_batch=4, max_wait_ms=200.0, queue_capacity=64,
        tenant_quota={"noisy": 2}, harvest=sink)
    qp = _qp()
    with service:
        service.prewarm(qp)
        # Stall dispatch long enough (max_wait 200ms, batch 4) that
        # the noisy tenant's 3rd submit finds its sub-queue full.
        t1 = service.submit(qp, tenant="noisy")
        t2 = service.submit(qp, tenant="noisy")
        with pytest.raises(QueueFull):
            service.submit(qp, tenant="noisy")
        t3 = service.submit(qp, tenant="quiet")
        for t in (t1, t2, t3):
            service.result(t, timeout=60)
        snap = service.snapshot()["tenants"]
        assert snap["noisy"]["rejected"] == 1
        assert snap["noisy"]["completed"] == 2
        assert snap["quiet"]["rejected"] == 0
        assert snap["quiet"]["completed"] == 1
        counts = {}
        for rec in sink.buffered():
            counts[rec["tenant"]] = counts.get(rec["tenant"], 0) + 1
        assert counts.get("noisy") == 2 and counts.get("quiet") == 1
        # /healthz carries the tenancy section; /metrics the labeled
        # tenant series (escaped ids pinned separately).
        payload = service._health_payload()
        assert payload["tenancy"]["tenants"]["noisy"]["rejected"] == 1
        assert payload["tenancy"]["quota_sheds"] == {"noisy": 1}
        text = prometheus_text(
            service.snapshot(),
            labeled_gauges=service._labeled_gauges())
        assert 'porqua_serve_tenant_rejected{tenant="noisy"} 1' in text


def test_untagged_requests_account_under_default_lane():
    service = SolveService(
        params=PARAMS, ladder=BucketLadder(n_rungs=(8,), m_rungs=(4,)),
        max_batch=2, max_wait_ms=2.0, queue_capacity=16)
    qp = _qp()
    with service:
        service.prewarm(qp)
        service.result(service.submit(qp), timeout=60)
    snap = service.snapshot()["tenants"]
    assert snap[DEFAULT_TENANT]["completed"] == 1


# ---------------------------------------------------------------------------
# workloads + contracts
# ---------------------------------------------------------------------------

def test_workload_library_selftest():
    from porqua_tpu.serve import workloads

    workloads.selftest()


def test_gc109_tenancy_identity_clean():
    from porqua_tpu.analysis.contracts import check_tenancy_identity

    assert check_tenancy_identity() == []
