"""Driver-contract tests for bench.py.

The benchmark artifact failed to record in rounds 1 AND 2 (a TPU-init
crash, then a blown wall-clock budget against a black-holed tunnel).
These tests pin the round-3 contract: bench.py always prints exactly
one parseable JSON line on stdout and exits 0 — under a forced-CPU run,
and under a global deadline too short for any device work.

Subprocess-based on purpose: the contract is about the executable the
driver invokes, not about importable internals.
"""

import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_ROOT, "bench.py")


def _run_bench(env_extra, timeout):
    env = dict(os.environ)
    env.update(env_extra)
    # The tests' own JAX_PLATFORMS must not leak: bench children decide
    # their platform via argv.
    proc = subprocess.run(
        [sys.executable, _BENCH], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=_ROOT,
    )
    return proc


def _parse_single_json_line(stdout):
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    return json.loads(lines[0])


@pytest.mark.slow
def test_forced_cpu_run_prints_valid_json():
    proc = _run_bench({
        "PORQUA_BENCH_PLATFORM": "cpu",
        "PORQUA_BENCH_DATES": "6",
        "PORQUA_BENCH_ASSETS": "32",
        "PORQUA_BENCH_WINDOW": "48",
        "PORQUA_BENCH_FALLBACK_DATES": "3",
        "PORQUA_BENCH_DEADLINE": "240",
    }, timeout=280)
    assert proc.returncode == 0, proc.stderr[-500:]
    payload = _parse_single_json_line(proc.stdout)
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in payload, f"missing {key}"
    assert payload["value"] > 0
    assert payload["device"] == "cpu"
    assert payload["fallback_reduced"] is True
    assert payload["fallback_dates"] == 3
    # A healthy forced-cpu run is annotated via "note", never "error".
    assert "error" not in payload
    assert "forced" in payload.get("note", "")
    # Quality fields present so speedups are falsifiable.
    assert payload["device_solved"] == 3
    assert payload["baseline_median_te"] > 0
    assert payload["device_median_te"] > 0
    # Round-5 contract: the fallback artifact is interpretable at full
    # size on its own — steady-state field plus a labeled linear
    # extrapolation of the reduced shard.
    assert payload["seconds_steady_state"] > 0
    assert payload["value_full_extrapolated"] >= payload["value"]
    assert "extrapolation" in payload
    assert payload["vs_baseline_full_extrapolated"] > 0


@pytest.mark.slow
def test_deadline_still_prints_json():
    """A deadline too short for any device stage must still produce the
    JSON line (with the partial-results error), exit 0, and do so
    within a few seconds of the deadline."""
    proc = _run_bench({
        "PORQUA_BENCH_PLATFORM": "cpu",
        "PORQUA_BENCH_DATES": "6",
        "PORQUA_BENCH_ASSETS": "32",
        "PORQUA_BENCH_WINDOW": "48",
        "PORQUA_BENCH_DEADLINE": "12",
    }, timeout=60)
    assert proc.returncode == 0, proc.stderr[-500:]
    payload = _parse_single_json_line(proc.stdout)
    assert "value" in payload and "vs_baseline" in payload
    assert payload["elapsed_s"] < 30
    # Either a stage was skipped for lack of budget or the alarm fired;
    # both must be visible in the error field.
    err = payload.get("error", "")
    assert ("deadline" in err or "no time left" in err
            or "no budget" in err), payload
