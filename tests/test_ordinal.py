"""Ordinal regression tests (reference ``example/ordinal_regression.ipynb``).

The reference fits statsmodels ``OrderedModel`` (probit/logit) on decile
rank labels. statsmodels is not in this image, so the parity reference
is an independent scipy/numpy MLE of the identical likelihood.
"""

import numpy as np
import pytest
import scipy.optimize
import scipy.stats

from porqua_tpu.models.ordinal import OrdinalRegression, decile_rank_labels


def _fit_broken_reason():
    """Probe whether ``OrdinalRegression.fit`` works in this
    environment. Under ``jax_enable_x64`` (the test conftest turns it
    on for float64 parity references), optax 0.2.3's
    ``value_and_grad_from_state`` traces its recompute ``lax.cond``
    with a float64 weak-type stored value against the model's float32
    nll — a TypeError at trace time. That is a jax/optax version-skew
    property of the environment, not of this code, so the
    fit-dependent tests skip with the live reason instead of failing
    (or xfail-masking a real future regression)."""
    rng = np.random.default_rng(0)
    X = rng.standard_normal((24, 2))
    y = np.searchsorted([-0.5, 0.5], X @ np.array([1.0, -0.5]))
    try:
        OrdinalRegression(distr="probit", max_iter=5).fit(X, y)
    except TypeError as exc:
        return ("OrdinalRegression.fit broken by the installed "
                f"jax/optax pair: {exc}")
    return None


_FIT_BROKEN = _fit_broken_reason()
needs_working_fit = pytest.mark.skipif(
    bool(_FIT_BROKEN), reason=_FIT_BROKEN or "")


@pytest.fixture(scope="module")
def ordinal_data():
    """Latent-variable data: y* = X beta + eps, discretized at cutpoints."""
    rng = np.random.default_rng(11)
    n, d, K = 1500, 3, 4
    X = rng.standard_normal((n, d))
    beta = np.array([1.0, -0.5, 0.25])
    cuts = np.array([-1.0, 0.0, 1.2])
    latent = X @ beta + rng.standard_normal(n)
    y = np.searchsorted(cuts, latent)
    return X, y, beta, cuts, K


def _numpy_nll(theta, X, y, K, distr):
    """Independent implementation of the ordered-model likelihood."""
    d = X.shape[1]
    beta = theta[:d]
    raw = theta[d:]
    cuts = np.concatenate([raw[:1], raw[0] + np.cumsum(np.exp(raw[1:]))])
    eta = X @ beta
    F = scipy.stats.norm.cdf if distr == "probit" else scipy.stats.logistic.cdf
    cdf = F(cuts[None, :] - eta[:, None])
    upper = np.concatenate([cdf, np.ones((len(eta), 1))], axis=1)
    lower = np.concatenate([np.zeros((len(eta), 1)), cdf], axis=1)
    p = (upper - lower)[np.arange(len(y)), y]
    return -np.mean(np.log(np.clip(p, 1e-12, None)))


@needs_working_fit
@pytest.mark.parametrize("distr", ["probit", "logit"])
def test_matches_scipy_mle(ordinal_data, distr):
    X, y, beta_true, _, K = ordinal_data
    model = OrdinalRegression(distr=distr).fit(X, y)

    d = X.shape[1]
    theta0 = np.zeros(d + K - 1)
    theta0[d] = -1.0
    ref = scipy.optimize.minimize(
        _numpy_nll, theta0, args=(X, y, K, distr), method="BFGS")
    ref_beta = ref.x[:d]
    ref_cuts = np.concatenate(
        [ref.x[d:d + 1], ref.x[d] + np.cumsum(np.exp(ref.x[d + 1:]))])

    np.testing.assert_allclose(model.beta_, ref_beta, atol=2e-2)
    np.testing.assert_allclose(model.cutpoints_, ref_cuts, atol=2e-2)
    assert model.nll_ == pytest.approx(ref.fun, abs=1e-4)


@needs_working_fit
def test_probit_recovers_generating_process(ordinal_data):
    X, y, beta_true, cuts_true, K = ordinal_data
    model = OrdinalRegression(distr="probit").fit(X, y)
    # MLE on 1500 samples should land near the generating parameters
    np.testing.assert_allclose(model.beta_, beta_true, atol=0.15)
    np.testing.assert_allclose(model.cutpoints_, cuts_true, atol=0.15)
    # in-sample accuracy well above the 1/K = 0.25 chance level
    acc = (model.predict(X) == y).mean()
    assert acc > 0.40


@needs_working_fit
def test_predict_proba_properties(ordinal_data):
    X, y, *_ = ordinal_data
    model = OrdinalRegression(distr="logit").fit(X, y)
    probs = model.predict_proba(X[:100])
    assert probs.shape == (100, model.n_classes)
    assert (probs >= 0).all()
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    # cutpoints strictly increasing
    assert (np.diff(model.cutpoints_) > 0).all()
    # expected rank is a monotone summary within [0, K-1]
    er = model.expected_rank(X[:100])
    assert er.min() >= 0 and er.max() <= model.n_classes - 1


def test_decile_rank_labels():
    import pandas as pd

    rng = np.random.default_rng(5)
    df = pd.DataFrame(rng.standard_normal((4, 20)))
    labels = decile_rank_labels(df, n_bins=10)
    assert labels.shape == df.shape
    assert labels.min().min() == 0 and labels.max().max() == 9
    # reference convention: rank 0 = highest return
    row = df.iloc[0]
    assert labels.iloc[0][row.idxmax()] == 0
    assert labels.iloc[0][row.idxmin()] == 9
    # Series variant
    s = decile_rank_labels(row, n_bins=5)
    assert s[row.idxmax()] == 0 and s[row.idxmin()] == 4
    # bins are even: 20 assets / 10 bins = exactly 2 per bin
    counts = labels.iloc[0].value_counts()
    assert (counts == 2).all()


def test_rank_labels_nan_handling():
    import pandas as pd

    s = pd.Series([0.1, np.nan, -0.2, 0.3], index=list("abcd"))
    out = decile_rank_labels(s, n_bins=3)
    assert "b" not in out.index  # NaN dropped
    assert out["d"] == 0 and out["c"] == 2  # descending convention
    df = pd.DataFrame([[0.1, np.nan, -0.2, 0.3]], columns=list("abcd"))
    out2 = decile_rank_labels(df, n_bins=3)
    assert pd.isna(out2.iloc[0]["b"])
    assert out2.iloc[0]["d"] == 0
