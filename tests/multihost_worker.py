"""Worker process for the two-process multihost test (not a pytest
module — spawned by ``tests/test_parallel.py::test_two_process_multihost``).

Each of the two processes contributes 4 virtual CPU devices, joins the
fleet through ``init_distributed`` (the repo's wrapper, including its
process-count consistency check), builds the hosts x dates hybrid mesh,
places one globally-sharded batch of tracking QPs, and solves it with
the SAME batched program as single-chip. Each process then checks its
own addressable shards against a locally-computed unsharded reference —
cross-process agreement follows because both references are
deterministic and identical.

Usage: multihost_worker.py <process_id> <num_processes> <port>
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
port = int(sys.argv[3])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

from porqua_tpu.parallel.mesh import (batch_sharding, init_distributed,
                                      make_multihost_mesh)
from porqua_tpu.qp.solve import SolverParams, solve_qp_batch
from porqua_tpu.tracking import build_tracking_qp, synthetic_universe

got = init_distributed(coordinator_address=f"localhost:{port}",
                       num_processes=nproc, process_id=pid)
assert got == nproc, (got, nproc)
assert len(jax.local_devices()) == 4
assert len(jax.devices()) == 4 * nproc

mesh = make_multihost_mesh()
assert mesh.devices.shape == (nproc, 4), mesh.devices.shape
assert mesh.axis_names == ("hosts", "dates")

# Deterministic batch, identical in every process.
B = 16
Xs, ys = synthetic_universe(jax.random.PRNGKey(5), n_dates=B, window=24,
                            n_assets=12, dtype=jnp.float64)
qp = jax.vmap(build_tracking_qp)(Xs, ys)
qp_np = jax.tree.map(np.asarray, qp)

# Global placement: the batch axis split over BOTH mesh axes (pure data
# parallelism — 2 dates per virtual chip). Each process provides the
# values for its own addressable shards out of the shared full array.
from jax.sharding import NamedSharding, PartitionSpec as P

sharding = NamedSharding(mesh, P(("hosts", "dates")))


def put_global(arr):
    spec = P(("hosts", "dates"), *([None] * (arr.ndim - 1)))
    s = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(arr.shape, s, lambda idx: arr[idx])


qp_global = jax.tree.map(put_global, qp_np)

params = SolverParams(max_iter=2000, eps_abs=1e-8, eps_rel=1e-8,
                      linsolve="chol")
sol = solve_qp_batch(qp_global, params)
jax.block_until_ready(sol.x)

# Local reference: the identical batch, unsharded, on this process's
# first device only.
ref = solve_qp_batch(jax.tree.map(jnp.asarray, qp_np), params)
ref_x = np.asarray(ref.x)
assert np.all(np.asarray(ref.status) == 1)

maxdiff = 0.0
n_rows = 0
for shard in sol.x.addressable_shards:
    rows = np.asarray(shard.data)
    idx = shard.index[0]
    maxdiff = max(maxdiff, float(np.max(np.abs(rows - ref_x[idx]))))
    n_rows += rows.shape[0]
assert n_rows == B // nproc, (n_rows, B, nproc)

# batch_sharding must agree with the placement this worker used.
assert batch_sharding(mesh, qp_np.P.ndim, 1).spec[0] == "hosts"

print(f"MULTIHOST OK pid={pid} procs={got} shard_rows={n_rows} "
      f"maxdiff={maxdiff:.2e}", flush=True)
assert maxdiff < 1e-12
