"""Cross-solver validation harness on the real MSCI dataset.

Automated port of the reference's de-facto correctness harness
(``example/compare_solver.ipynb`` cells 6/8/12): solve the same
LeastSquares index-tracking problem with the device ADMM solver and an
independent CPU reference (scipy SLSQP here; the notebook used the
qpsolvers backends), and compare the full metric set the notebook
defines — primal residual, dual residual, duality gap, constraint
residuals |Ax-b| / max(Gx-h), and the objective value at the solution.
"""

import os

import numpy as np
import pytest
import scipy.optimize

import jax.numpy as jnp

from porqua_tpu.data_loader import load_data_msci
from porqua_tpu.optimization import LeastSquares
from porqua_tpu.constraints import Constraints
from porqua_tpu.optimization_data import OptimizationData
from porqua_tpu.qp import SolverParams, Status

DATA_PATH = "/root/reference/data/"
TIGHT = SolverParams(eps_abs=1e-9, eps_rel=1e-9, max_iter=20000)

pytestmark = pytest.mark.skipif(
    not os.path.isdir(DATA_PATH),
    reason="reference data mount not present",
)


@pytest.fixture(scope="module")
def msci():
    data = load_data_msci(path=DATA_PATH)
    X = data["return_series"].tail(1260)
    y = data["bm_series"].reindex(X.index).iloc[:, 0]
    return X, y


@pytest.fixture(scope="module")
def solved(msci):
    X, y = msci
    universe = list(X.columns)
    opt = LeastSquares(dtype=jnp.float64, **TIGHT.__dict__)
    opt.constraints = Constraints(selection=universe)
    opt.constraints.add_budget()
    opt.constraints.add_box("LongOnly")
    opt.set_objective(OptimizationData(align=False, return_series=X, bm_series=y))
    assert opt.solve()
    return opt, X, y


def test_msci_tracking_solution_quality(solved):
    """The compare_solver metric set, at interior-point-grade tolerances."""
    opt, X, y = solved
    sol = opt.solution
    assert int(sol.status) == Status.SOLVED
    assert float(sol.prim_res) < 1e-8
    assert float(sol.dual_res) < 1e-8
    assert float(sol.duality_gap) < 1e-7

    w = np.asarray(sol.x)[: X.shape[1]]
    assert abs(w.sum() - 1.0) < 1e-9          # |Ax - b|
    assert w.min() > -1e-10 and w.max() < 1.0 + 1e-10  # box


def test_msci_matches_scipy_reference(solved):
    opt, X, y = solved
    n = X.shape[1]
    P = 2 * X.T.to_numpy() @ X.to_numpy()
    q = -2 * X.T.to_numpy() @ y.to_numpy()

    ref = scipy.optimize.minimize(
        lambda w: 0.5 * w @ P @ w + q @ w,
        x0=np.full(n, 1.0 / n),
        jac=lambda w: P @ w + q,
        bounds=[(0, 1)] * n,
        constraints=[{"type": "eq", "fun": lambda w: w.sum() - 1,
                      "jac": lambda w: np.ones(n)}],
        method="SLSQP",
        options={"ftol": 1e-16, "maxiter": 2000},
    )
    w_dev = np.asarray(opt.solution.x)[:n]
    # Objective parity is the solver-independent criterion (weights can
    # differ along near-degenerate directions of the Gram matrix).
    obj_dev = 0.5 * w_dev @ P @ w_dev + q @ w_dev
    assert obj_dev <= ref.fun + 1e-10
    # Tracking error parity — the acceptance bar from BASELINE.json.
    te_dev = np.sqrt(np.mean((X.to_numpy() @ w_dev - y.to_numpy()) ** 2))
    te_ref = np.sqrt(np.mean((X.to_numpy() @ ref.x - y.to_numpy()) ** 2))
    assert te_dev <= te_ref * (1 + 1e-6)


def test_msci_objective_value_consistency(solved):
    """Solver-reported objective == recomputed 0.5 x'Px + q'x + const
    (the reference's tearDown assertion, tests_quadratic_program.py:81)."""
    opt, X, y = solved
    reported = float(opt.solution.obj_val)
    recomputed = float(opt.model.objective_value(opt.solution.x))
    assert reported == pytest.approx(recomputed, rel=1e-12)
    # And the constant term makes it the actual squared tracking distance.
    w = np.asarray(opt.solution.x)[: X.shape[1]]
    direct = float(((X.to_numpy() @ w - y.to_numpy()) ** 2).sum())
    assert reported == pytest.approx(direct, rel=1e-6)


def test_degenerate_2020_window_converges():
    """Regression pin for the equality-row limit cycle (round 3).

    The 2020-10-01 window is primal degenerate under a 0.5 upper box:
    the optimal budget row is the sum of two box-active variables. With
    the OSQP-style x1000 equality-row step weighting (rho_eq_scale 1e3,
    the round-1/2 default) the iteration locked into a ~1e-4 limit
    cycle — 4000+ stalled iterations and a FAILED solve on a
    cond(P)=588 problem; with the round-3 default (1.0) it converges in
    ~50 iterations. Solve all four 2020 quarterly windows with library
    defaults and require clean convergence, well under the old stall.
    """
    data = load_data_msci(path=DATA_PATH)
    X_all = data["return_series"]
    y_all = data["bm_series"].iloc[:, 0]
    for d in ("2020-01-01", "2020-04-01", "2020-07-01", "2020-10-01"):
        Xw = X_all.loc[:d].tail(252).dropna(axis=1)
        yw = y_all.loc[:d].tail(252)
        ls = LeastSquares(n_max=24)  # one pooled jit shape for all windows
        ls.constraints = Constraints(selection=list(Xw.columns))
        ls.constraints.add_budget(rhs=1.0, sense="=")
        ls.constraints.add_box("LongOnly", upper=0.5)
        ls.set_objective(OptimizationData(
            align=False, return_series=Xw, bm_series=yw))
        assert ls.solve(), f"{d}: solve failed"
        assert int(ls.solution.status) == Status.SOLVED
        assert int(ls.solution.iters) <= 500, (
            f"{d}: {int(ls.solution.iters)} iterations — stall regression")


def test_quarterly_sweep_all_windows_solve():
    """Robustness sweep: every quarterly rebalance window 2005-2023 on
    the real MSCI universe must solve with library defaults (budget +
    LongOnly box) — the class of real-data degeneracies that synthetic
    factor batches never exhibit (this is how the 2020 stall was
    found)."""
    import pandas as pd

    data = load_data_msci(path=DATA_PATH)
    X_all = data["return_series"]
    y_all = data["bm_series"].iloc[:, 0]
    dates = [str(d.date()) for d in
             pd.date_range("2005-01-01", "2023-01-01", freq="QS")]
    failed = []
    for d in dates:
        Xw = X_all.loc[:d].tail(252).dropna(axis=1)
        if Xw.shape[0] < 252:
            continue
        yw = y_all.loc[:d].tail(252)
        # Pool shapes (n_max): post-dropna universes vary by window, and
        # a distinct jit shape per window would compile ~70 XLA programs
        # on this 1-core host — pad to one static shape instead.
        ls = LeastSquares(n_max=24)
        ls.constraints = Constraints(selection=list(Xw.columns))
        ls.constraints.add_budget(rhs=1.0, sense="=")
        ls.constraints.add_box("LongOnly")
        ls.set_objective(OptimizationData(
            align=False, return_series=Xw, bm_series=yw))
        if not ls.solve() or int(ls.solution.status) != Status.SOLVED:
            failed.append(d)
    assert not failed, f"unsolved windows: {failed}"


def test_serial_and_batched_engines_agree_on_2020():
    """Engine parity on real data through the COVID regime: the serial
    per-date engine and the one-XLA-program batched engine must produce
    the same weights on the 2020 quarterly backtest (the drive that
    exposed the round-3 equality-row stall — back then the two engines
    failed on *different* dates). No x0 builder is configured, so both
    engines solve each date cold; warm-start coupling is exercised by
    the scan tests."""
    import pandas as pd

    from porqua_tpu.backtest import Backtest, BacktestService
    from porqua_tpu.batch import run_batch
    from porqua_tpu.builders import (OptimizationItemBuilder,
                                     SelectionItemBuilder,
                                     bibfn_bm_series,
                                     bibfn_box_constraints,
                                     bibfn_budget_constraint,
                                     bibfn_return_series,
                                     bibfn_selection_data)

    data = load_data_msci(path=DATA_PATH)
    rebdates = [str(d.date()) for d in
                pd.date_range("2020-01-01", "2020-12-31", freq="QS")]
    bs = BacktestService(
        data={"return_series": data["return_series"],
              "bm_series": data["bm_series"]},
        selection_item_builders={
            "data": SelectionItemBuilder(bibfn=bibfn_selection_data)},
        optimization_item_builders={
            "rs": OptimizationItemBuilder(bibfn=bibfn_return_series,
                                          width=252),
            "bm": OptimizationItemBuilder(bibfn=bibfn_bm_series, width=252),
            "budget": OptimizationItemBuilder(
                bibfn=bibfn_budget_constraint, budget=1),
            "box": OptimizationItemBuilder(bibfn=bibfn_box_constraints,
                                           upper=0.5),
        },
        optimization=LeastSquares(),
        settings={"rebdates": rebdates, "quiet": True},
    )
    bt = Backtest()
    bt.run(bs)
    W_serial = bt.strategy.get_weights_df()
    W_batch = run_batch(bs).strategy.get_weights_df()

    # Every date solves in both engines (weights sum to the budget)...
    np.testing.assert_allclose(W_serial.sum(axis=1), 1.0, atol=1e-6)
    np.testing.assert_allclose(W_batch.sum(axis=1), 1.0, atol=1e-6)
    # ...and the engines agree to f32 solver tolerance.
    assert float((W_serial - W_batch).abs().to_numpy().max()) < 1e-4


def test_lad_prox_defaults_on_real_windows():
    """Round 5: the promoted LAD solver overlay (halpern + alpha 1.8 +
    rho0 60 + rho_l1_scale 10) on real MSCI year-windows, objective-
    checked against a per-window f64 IPM oracle on the epigraph form.
    A 9-window sweep 1999-2023 measured a worst gap of +1.84e-3
    (BASELINE.md round-5 notes); these two windows — the 2007-08
    crisis year and the worst-gap 2016-17 window — pin the real-data
    behavior in the suite."""
    from porqua_tpu.optimization import LAD
    from porqua_tpu.qp.ipm import solve_ipm

    data = load_data_msci(path=DATA_PATH)
    X_all = data["return_series"]
    y_all = data["bm_series"]

    for start in ("2007-09-12", "2016-05-23"):
        X = X_all.loc[X_all.index >= start].iloc[:252]
        y = y_all.reindex(X.index)

        def build(**kw):
            lad = LAD(dtype=jnp.float64, **kw)
            lad.constraints = Constraints(selection=list(X.columns))
            lad.constraints.add_budget()
            lad.constraints.add_box("LongOnly")
            lad.set_objective(OptimizationData(
                align=False, return_series=X, bm_series=y))
            return lad

        lad = build()
        assert lad.solve(), start
        # Pin CONVERGENCE, not just objective quality: LAD defaults
        # allow_suboptimal=True, so solve() alone would also accept a
        # MAX_ITER stall (the pre-round-5 pathology this test guards).
        # The 9-window sweep's worst case was 5,600 iterations; 10,000
        # leaves margin while catching a 16k-40k regression.
        assert int(lad.solution.status) == Status.SOLVED, start
        assert int(lad.solution.iters) <= 10000, (
            start, int(lad.solution.iters))
        w = np.asarray(lad.solution.x)[:X.shape[1]]
        Xl = np.log((1 + X).cumprod()).to_numpy()
        yl = np.log((1 + y).cumprod()).to_numpy().ravel()
        obj = float(np.sum(np.abs(Xl @ w - yl)))

        ipm = solve_ipm(build(prox_form=False).canonical_parts(),
                        tol=1e-9)
        w_ipm = np.asarray(ipm.x)[:X.shape[1]]
        obj_ipm = float(np.sum(np.abs(Xl @ w_ipm - yl)))
        assert obj <= obj_ipm * (1 + 5e-3), (start, obj, obj_ipm)
        np.testing.assert_allclose(np.sum(w), 1.0, atol=1e-6)
        assert float(np.min(w)) > -1e-6, start
