"""Contracts for the NAPG backend (``SolverParams(method="napg")``).

Nesterov-accelerated projected gradient is the third solver backend,
aimed at the box-only tracking regime (box bounds + a budget row —
the most common serve bucket). These tests pin what the routing
subsystem stands on:

* the steppable NAPG API (``napg_init`` / ``napg_segment_step``) is
  bit-identical to the fused ``napg_solve`` while_loop (same compiled
  segment program — the compaction/continuous hoist cannot drift);
* solutions agree with the ADMM backend on the same problems (shared
  KKT residual measure, shared finalize), so a routing flip changes
  wall-clock, never answers;
* the adaptive (gradient) restart actually fires and is observable
  through the convergence rings (third slot = cumulative restart
  count, as for PDHG);
* MAX_ITER retirement + active-set polish fallback work for NAPG
  lanes exactly as for ADMM/PDHG lanes;
* the backend-agnostic drivers (vmapped batch solve, compacting
  driver) accept ``method="napg"`` and agree lane-for-lane.

The test family is box + budget QPs (dense factor P, single budget
row, box bounds) — NAPG's winning regime, where the per-iteration
prox reduces to one scalar dual bisection — small enough for CPU CI.
"""

import dataclasses
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from porqua_tpu.compaction import CompactingDriver
from porqua_tpu.obs.rings import ring_history
from porqua_tpu.qp.admm import Status
from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.napg import napg_init, napg_segment_step, napg_solve
from porqua_tpu.qp.ruiz import equilibrate
from porqua_tpu.qp.solve import SolverParams, solve_qp, solve_qp_batch

# Tight-ish eps with a short check interval: NAPG converges in a few
# dozen iterations on this family, so check_interval=10 makes every
# lane take multiple segments (the stepper-parity precondition).
PARAMS = SolverParams(method="napg", max_iter=2000, eps_abs=1e-6,
                      eps_rel=1e-6, polish=False, check_interval=10)

N, M, B = 32, 1, 6


def _box_qp(rng, n=N, box=0.1):
    """Dense factor-model P, one budget row, box bounds — the tracking
    serve bucket at test size (NAPG's target regime)."""
    F = rng.standard_normal((max(2, n // 4), n))
    P = F.T @ F / n + 0.05 * np.eye(n)
    return CanonicalQP.build(
        P, rng.standard_normal(n) * 0.1,
        C=np.ones((1, n)), l=np.ones(1), u=np.ones(1),
        lb=np.zeros(n), ub=np.full(n, box))


def _make_batch():
    rng = np.random.default_rng(7)
    return stack_qps([_box_qp(rng) for _ in range(B)])


@pytest.fixture(scope="module")
def batch():
    return _make_batch()


# ---------------------------------------------------------------------------
# steppable API
# ---------------------------------------------------------------------------

def test_segment_step_matches_napg_solve(batch):
    """A host loop over jitted napg_segment_step reproduces the fused
    while_loop bit-for-bit (the twin of the ADMM/PDHG stepper contracts
    — same hoisted segment program)."""
    qp = jax.tree.map(lambda a: a[0], batch)
    scaled, scaling = equilibrate(qp, iters=PARAMS.scaling_iters)

    @functools.partial(jax.jit, static_argnames=("params",))
    def step(carry, s, sc, params):
        return napg_segment_step(carry, s, sc, params)[0]

    @functools.partial(jax.jit, static_argnames=("params",))
    def fused_solve(s, sc, params):
        return napg_solve(s, sc, params)

    carry = jax.jit(lambda q: napg_init(q, PARAMS))(scaled)
    n_segments = 0
    while (int(carry.state.status) == Status.RUNNING
           and int(carry.state.iters) < PARAMS.max_iter):
        carry = step(carry, scaled, scaling, PARAMS)
        n_segments += 1
    assert n_segments >= 2, "family must take multiple segments"
    ref = fused_solve(scaled, scaling, PARAMS)
    got = carry.state._replace(status=jnp.where(
        carry.state.status == Status.RUNNING, Status.MAX_ITER,
        carry.state.status).astype(jnp.int32))
    for name in ("x", "z", "w", "y", "mu", "rho_bar", "iters", "status",
                 "prim_res", "dual_res"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(ref, name)), err_msg=name)


def test_segment_step_never_retires_max_iter(batch):
    """The stepper leaves budget enforcement to the orchestrator: a
    lane past ``max_iter`` keeps status RUNNING until a driver (or the
    fused solve's exit) retires it."""
    qp = jax.tree.map(lambda a: a[0], batch)
    short = dataclasses.replace(PARAMS, max_iter=10)
    scaled, scaling = equilibrate(qp, iters=short.scaling_iters)
    carry = jax.jit(lambda q: napg_init(q, short))(scaled)

    @functools.partial(jax.jit, static_argnames=("params",))
    def step(c, s, sc, params):
        return napg_segment_step(c, s, sc, params)[0]

    for _ in range(3):  # 3 segments = 30 iters >> max_iter=10
        carry = step(carry, scaled, scaling, short)
    assert int(carry.state.iters) == 30
    assert int(carry.state.status) == Status.RUNNING


# ---------------------------------------------------------------------------
# solution agreement with the ADMM backend
# ---------------------------------------------------------------------------

def test_napg_agrees_with_admm(batch):
    """Both backends certify SOLVED on every lane and land on the same
    optimum (shared residual measure -> comparable certificates; the
    routing flip must never change answers)."""
    admm_params = dataclasses.replace(PARAMS, method="admm")
    sol_n = solve_qp_batch(batch, PARAMS)
    sol_a = solve_qp_batch(batch, admm_params)
    assert np.all(np.asarray(sol_n.status) == Status.SOLVED), (
        np.asarray(sol_n.status))
    assert np.all(np.asarray(sol_a.status) == Status.SOLVED)
    x_n, x_a = np.asarray(sol_n.x), np.asarray(sol_a.x)
    np.testing.assert_allclose(x_n, x_a, atol=2e-3)
    obj_n, obj_a = np.asarray(sol_n.obj_val), np.asarray(sol_a.obj_val)
    np.testing.assert_allclose(obj_n, obj_a, rtol=1e-3, atol=1e-5)
    # Certificates are real KKT residuals for this backend too.
    assert float(np.max(np.asarray(sol_n.prim_res))) < 1e-4
    assert float(np.max(np.asarray(sol_n.dual_res))) < 1e-4


def test_napg_feasible_on_budget_row(batch):
    """Every NAPG iterate is prox-feasible by construction: the
    returned x satisfies the budget row and box to tight tolerance
    (the projection is exact, not penalized)."""
    sol = solve_qp_batch(batch, PARAMS)
    x = np.asarray(sol.x)
    np.testing.assert_allclose(x.sum(axis=1), 1.0, atol=1e-5)
    assert float(x.min()) >= -1e-7
    assert float(x.max()) <= 0.1 + 1e-7


# ---------------------------------------------------------------------------
# restarts + rings
# ---------------------------------------------------------------------------

def test_restarts_fire_and_ring_records_them(batch):
    """The gradient restart actually triggers on this family, and the
    rings' third slot carries the cumulative restart count (decoded
    chronologically it is non-decreasing and ends at the carry's
    total) — the trajectory diagnostic obs/rings exposes."""
    qp = jax.tree.map(lambda a: a[0], batch)
    ringed = dataclasses.replace(PARAMS, ring_size=64)
    scaled, scaling = equilibrate(qp, iters=ringed.scaling_iters)
    carry = jax.jit(lambda q: napg_init(q, ringed))(scaled)

    @functools.partial(jax.jit, static_argnames=("params",))
    def step(c, s, sc, params):
        return napg_segment_step(c, s, sc, params)[0]

    while (int(carry.state.status) == Status.RUNNING
           and int(carry.state.iters) < ringed.max_iter):
        carry = step(carry, scaled, scaling, ringed)

    n_restarts = int(carry.restart_count)
    assert n_restarts >= 1, "restart machinery never fired"
    hist = ring_history(carry.state.ring_prim, carry.state.ring_dual,
                        carry.state.ring_rho, int(carry.state.iters),
                        ringed.check_interval)
    counts = hist["rho"]  # NAPG: cumulative restart count per segment
    assert counts == sorted(counts), counts
    assert int(counts[-1]) == n_restarts, (counts, n_restarts)
    # The trajectory converged: final ring sample equals the state's
    # residuals exactly (polish=False contract from qp/solve.py).
    assert hist["prim_res"][-1] == float(carry.state.prim_res)
    assert hist["dual_res"][-1] == float(carry.state.dual_res)


# ---------------------------------------------------------------------------
# MAX_ITER retirement + polish fallback
# ---------------------------------------------------------------------------

def test_max_iter_polish_fallback(batch):
    """A NAPG lane retired out of budget still gets the active-set
    polish and is re-graded SOLVED when the polished point meets
    tolerance — the same finalize contract as ADMM/PDHG lanes."""
    qp = jax.tree.map(lambda a: a[0], batch)
    starved = dataclasses.replace(PARAMS, max_iter=20)
    raw = solve_qp(qp, starved)
    assert int(raw.status) == Status.MAX_ITER
    polished = solve_qp(qp, dataclasses.replace(starved, polish=True))
    assert int(polished.iters) == 20  # polish adds accuracy, not iters
    assert float(polished.prim_res) <= float(raw.prim_res)
    assert float(polished.dual_res) <= float(raw.dual_res)
    # On this well-conditioned family one polish pass reaches
    # tolerance from 20 NAPG iterations -> the re-grade fires.
    assert int(polished.status) == Status.SOLVED


# ---------------------------------------------------------------------------
# backend-agnostic drivers
# ---------------------------------------------------------------------------

def test_compaction_parity_with_napg(batch):
    """The compacting driver is backend-agnostic: with method="napg"
    lanes agree with the vmapped fused solve in the original lane
    order with zero post-prewarm compiles. Statuses and iteration
    counts — what serve dispatch and harvest reconciliation stand
    on — are bit-equal. The continuous quantities are pinned to ulp
    tolerance rather than bitwise: NAPG lanes retire at widely spread
    iteration counts, so (unlike the PDHG/ADMM parity families) this
    family exercises the batch-1 rung of the repack ladder, where
    XLA:CPU lowers the factor matvec with a different accumulation
    order — the identical per-lane program rounds the last ulp
    differently. (PDHG has the same property; its test family just
    never repacks down to one lane.)"""
    fused = solve_qp_batch(batch, PARAMS)
    driver = CompactingDriver(PARAMS)
    compiled = driver.prewarm(B, N, M)
    assert compiled > 0
    sol, rep = driver.solve(batch)
    assert rep.compiles == 0, "prewarmed solve must not compile"
    status = np.asarray(fused.status)
    assert np.all(status == Status.SOLVED)
    np.testing.assert_array_equal(np.asarray(sol.status), status)
    np.testing.assert_array_equal(np.asarray(sol.iters),
                                  np.asarray(fused.iters))
    for name in ("x", "z", "y", "mu", "prim_res", "dual_res"):
        np.testing.assert_allclose(
            np.asarray(getattr(sol, name)),
            np.asarray(getattr(fused, name)), atol=1e-7, rtol=1e-6,
            err_msg=name)
