"""LSTM selection model tests (reference ``example/lstm.ipynb`` parity).

The reference workflow: sliding 100-day windows -> LSTM(32) -> Dropout
-> Dense(n_assets) next-day-return predictions, Adam/MSE training,
rank-quality scored with NDCG (cells 1-10). These tests exercise the
same contract at toy scale on a synthetic AR(1) universe where the
next-day return is predictable from the window.
"""

import os

import numpy as np
import pytest

from porqua_tpu.models import (
    make_windows,
    ndcg,
    train_lstm,
    lstm_selection_scores,
)


@pytest.fixture(scope="module")
def ar1_data():
    """AR(1) returns: next-day return is strongly predictable."""
    rng = np.random.default_rng(7)
    T, n = 400, 6
    phi = np.linspace(0.85, 0.95, n)
    eps = 0.05 * rng.standard_normal((T, n))
    X = np.zeros((T, n))
    for t in range(1, T):
        X[t] = phi * X[t - 1] + eps[t]
    return X


def test_make_windows_shapes_and_alignment(ar1_data):
    X, y = make_windows(ar1_data, window=10)
    assert X.shape == (390, 10, 6)
    assert y.shape == (390, 6)
    # no look-ahead: y[i] is the row immediately after window i
    np.testing.assert_array_equal(X[5][-1], ar1_data[14])
    np.testing.assert_array_equal(y[5], ar1_data[15])


def test_train_lstm_learns_ar1(ar1_data):
    X, y = make_windows(ar1_data, window=10)
    model = train_lstm(X, y, hidden=16, epochs=30, batch_size=64,
                       learning_rate=3e-3, seed=0)
    # loss decreases materially over training
    assert model.loss_history[-1] < 0.5 * model.loss_history[0]
    # predictions correlate with realized next-day returns
    pred = model.predict(X[-50:])
    corr = np.corrcoef(pred.ravel(), y[-50:].ravel())[0, 1]
    assert corr > 0.5


def test_lstm_save_load_roundtrip(tmp_path, ar1_data):
    X, y = make_windows(ar1_data, window=10)
    model = train_lstm(X, y, hidden=8, epochs=2, seed=1)
    before = model.predict(X[:3])
    path = str(tmp_path / "lstm.msgpack")
    model.save(path)
    model2 = train_lstm(X[:32], y[:32], hidden=8, epochs=1, seed=2)
    model2.load_params(path)
    np.testing.assert_allclose(model2.predict(X[:3]), before, atol=1e-6)


def test_ndcg_matches_sklearn():
    sklearn = pytest.importorskip("sklearn.metrics")
    rng = np.random.default_rng(3)
    scores = rng.standard_normal((5, 12))
    rel = rng.integers(0, 5, (5, 12)).astype(float)
    for k in (None, 5):
        ours = np.asarray(ndcg(scores, rel, k=k))
        theirs = np.array([
            sklearn.ndcg_score(rel[i:i + 1], scores[i:i + 1],
                               k=k if k is not None else 12)
            for i in range(5)
        ])
        np.testing.assert_allclose(ours, theirs, atol=1e-6)


def test_ndcg_perfect_ranking_is_one():
    rel = np.array([3.0, 2.0, 1.0, 0.0])
    assert float(ndcg(rel, rel)) == pytest.approx(1.0)


def test_lstm_selection_scores_bibfn_contract(ar1_data):
    import pandas as pd

    class FakeService:
        pass

    bs = FakeService()
    dates = pd.bdate_range("2015-01-01", periods=ar1_data.shape[0])
    bs.data = {"return_series": pd.DataFrame(
        ar1_data, index=dates, columns=[f"A{i}" for i in range(6)])}

    out = lstm_selection_scores(
        bs, rebdate=str(dates[-1].date()), window=10, train_windows=100,
        epochs=3, hidden=8, top_k=3)
    # same column contract as the LTR scorer (models/ltr.py)
    assert list(out.columns) == ["values", "binary"]
    assert out.shape == (6, 2)
    assert out["binary"].sum() == 3
    assert set(out["binary"].unique()) <= {0, 1}


REF_KERAS = "/root/reference/model/lstm_msci.keras"


@pytest.mark.skipif(
    not os.path.exists(REF_KERAS),
    reason="reference saved model not mounted",
)
class TestReferenceModelParity:
    """VERDICT item 10: load the reference's trained Keras LSTM
    (model/lstm_msci.keras) and demonstrate the workflow of
    example/lstm.ipynb cell 10 against it — no tensorflow needed."""

    def _numpy_keras_lstm(self, X, W, U, b, Wd, bd):
        """Forward pass with Keras LSTM semantics (gate order i,f,c,o;
        relu cell activation per the saved config) in plain numpy."""
        H = U.shape[0]
        relu = lambda v: np.maximum(v, 0.0)
        sig = lambda v: 1.0 / (1.0 + np.exp(-v))
        outs = []
        for x_seq in X:
            h = np.zeros(H); c = np.zeros(H)
            for t in range(x_seq.shape[0]):
                zz = x_seq[t] @ W + h @ U + b
                i, f, g, o = (zz[:H], zz[H:2*H], zz[2*H:3*H], zz[3*H:])
                c = sig(f) * c + sig(i) * relu(g)
                h = sig(o) * relu(c)
            outs.append(h @ Wd + bd)
        return np.stack(outs)

    def test_forward_matches_numpy_reference(self, rng):
        import io
        import zipfile

        import h5py

        from porqua_tpu.models.lstm import load_reference_lstm

        model = load_reference_lstm(REF_KERAS)
        with zipfile.ZipFile(REF_KERAS) as z:
            with h5py.File(io.BytesIO(z.read("model.weights.h5")), "r") as f:
                W = np.asarray(f["layers/lstm/cell/vars/0"], np.float64)
                U = np.asarray(f["layers/lstm/cell/vars/1"], np.float64)
                b = np.asarray(f["layers/lstm/cell/vars/2"], np.float64)
                Wd = np.asarray(f["layers/dense/vars/0"], np.float64)
                bd = np.asarray(f["layers/dense/vars/1"], np.float64)

        X = rng.standard_normal((3, 24, 100)) * 0.01
        got = model.predict(X)
        want = self._numpy_keras_lstm(X, W, U, b, Wd, bd)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_ndcg_workflow_on_msci(self):
        """Score the reference's trained model with our NDCG on real
        MSCI data (the cell-10 evaluation), and run our own freshly
        trained ranker through the identical harness. Both must produce
        valid NDCG in (0, 1]; the comparison is printed for BASELINE
        documentation."""
        from porqua_tpu.data_loader import load_data_msci
        from porqua_tpu.models.lstm import (
            load_reference_lstm, reference_lstm_windows)

        data = load_data_msci(path="/root/reference/data/")
        returns = data["return_series"].tail(400)
        X_ref, y = reference_lstm_windows(returns.values.astype(np.float32),
                                          window=100)
        X_ref, y = X_ref[-40:], y[-40:]

        model = load_reference_lstm(REF_KERAS)
        pred = model.predict(X_ref)
        assert pred.shape == (40, 24)
        assert np.all(np.isfinite(pred))

        rel = np.argsort(np.argsort(y, axis=1), axis=1).astype(float)
        ref_ndcg = float(np.mean(np.asarray(ndcg(pred, rel, k=10))))
        assert 0.0 < ref_ndcg <= 1.0
        print(f"reference saved-model NDCG@10 on MSCI tail: {ref_ndcg:.3f}")


REPO_ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "model", "lstm_msci_flax.msgpack")


@pytest.mark.skipif(
    not (os.path.exists(REPO_ARTIFACT)
         and os.path.isdir("/root/reference/data/")),
    reason="shipped artifact or reference data missing",
)
def test_shipped_artifact_loads_and_ranks():
    """The repo ships a trained ranker (model/lstm_msci_flax.msgpack,
    the analog of the reference's model/lstm_msci.keras). It must load
    into a fresh module and rank the MSCI held-out tail above chance."""
    from porqua_tpu.data_loader import load_data_msci
    from porqua_tpu.models.lstm import (
        LSTMRanker, TrainedLSTM, make_windows)

    data = load_data_msci(path="/root/reference/data/")
    returns = data["return_series"].tail(400)
    X, y = make_windows(returns.values, 100)
    X, y = X[-50:], y[-50:]

    module = LSTMRanker(n_assets=returns.shape[1], hidden=32)
    import jax

    params = module.init(jax.random.PRNGKey(0), X[:1].astype(np.float32),
                         deterministic=True)["params"]
    model = TrainedLSTM(module=module, params=params,
                        loss_history=np.zeros(0))
    model.load_params(REPO_ARTIFACT)

    pred = model.predict(X)
    rel = np.argsort(np.argsort(y, axis=1), axis=1).astype(float)
    got = float(np.mean(np.asarray(ndcg(pred, rel, k=10))))
    # Chance NDCG@10 for 24 graded items is ~0.56 with small variance;
    # the shipped artifact scores ~0.63 on this tail.
    rng = np.random.default_rng(0)
    chance = [
        float(np.mean(np.asarray(ndcg(
            np.stack([rng.permutation(24).astype(float)
                      for _ in range(len(rel))]), rel, k=10))))
        for _ in range(10)
    ]
    assert got > np.mean(chance) + 2 * np.std(chance), (got, np.mean(chance))
