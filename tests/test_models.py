"""ML selection models: learning-to-rank scoring bibfn."""

import numpy as np
import pandas as pd

from porqua_tpu.backtest import BacktestService
from porqua_tpu.builders import SelectionItemBuilder, bibfn_selection_ltr
from porqua_tpu.optimization import EmptyOptimization


def make_bs(rng, n_assets=12, n_dates=10):
    """Monthly feature cross-sections where feature 0 predicts returns."""
    assets = [f"S{i}" for i in range(n_assets)]
    days = pd.bdate_range("2022-01-03", periods=n_dates * 21 + 42)
    skill = rng.standard_normal(n_assets) * 0.002

    returns = pd.DataFrame(
        rng.standard_normal((len(days), n_assets)) * 0.005 + skill,
        index=days, columns=assets,
    )
    feat_dates = days[::21][:n_dates]
    frames = {}
    for d in feat_dates:
        frames[d] = pd.DataFrame(
            {
                "signal": skill + rng.standard_normal(n_assets) * 1e-4,
                "noise": rng.standard_normal(n_assets),
            },
            index=assets,
        )
    features = pd.concat(frames, axis=0)
    return BacktestService(
        data={"return_series": returns, "features": features},
        selection_item_builders={
            "ltr": SelectionItemBuilder(bibfn=bibfn_selection_ltr, top_k=4),
        },
        optimization_item_builders={},
        optimization=EmptyOptimization(),
        settings={"rebdates": [str(feat_dates[-1].date())]},
    )


def test_ltr_scores_rank_skilled_assets(rng):
    bs = make_bs(rng)
    rebdate = bs.settings["rebdates"][0]
    bs.build_selection(rebdate)

    out = bs.selection.filtered["ltr"]
    assert set(out.columns) == {"values", "binary"}
    assert out["binary"].sum() == 4
    # The learned scores must recover the planted skill ordering: the
    # top-4 selected should be mostly the truly-best assets.
    true_top = set(
        pd.Series(
            bs.data["return_series"].mean(), index=out.index
        ).nlargest(4).index
    )
    picked = set(out.index[out["binary"] == 1])
    assert len(picked & true_top) >= 3
    # And the selection machinery narrowed the universe accordingly.
    assert len(bs.selection.selected) == 4
