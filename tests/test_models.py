"""ML selection models: learning-to-rank scoring bibfn."""

import numpy as np
import pandas as pd

from porqua_tpu.backtest import BacktestService
from porqua_tpu.builders import SelectionItemBuilder, bibfn_selection_ltr
from porqua_tpu.optimization import EmptyOptimization


def make_bs(rng, n_assets=12, n_dates=10):
    """Monthly feature cross-sections where feature 0 predicts returns."""
    assets = [f"S{i}" for i in range(n_assets)]
    days = pd.bdate_range("2022-01-03", periods=n_dates * 21 + 42)
    skill = rng.standard_normal(n_assets) * 0.002

    returns = pd.DataFrame(
        rng.standard_normal((len(days), n_assets)) * 0.005 + skill,
        index=days, columns=assets,
    )
    feat_dates = days[::21][:n_dates]
    frames = {}
    for d in feat_dates:
        frames[d] = pd.DataFrame(
            {
                "signal": skill + rng.standard_normal(n_assets) * 1e-4,
                "noise": rng.standard_normal(n_assets),
            },
            index=assets,
        )
    features = pd.concat(frames, axis=0)
    return BacktestService(
        data={"return_series": returns, "features": features},
        selection_item_builders={
            "ltr": SelectionItemBuilder(bibfn=bibfn_selection_ltr, top_k=4),
        },
        optimization_item_builders={},
        optimization=EmptyOptimization(),
        settings={"rebdates": [str(feat_dates[-1].date())]},
    )


def test_ltr_scores_rank_skilled_assets(rng):
    bs = make_bs(rng)
    rebdate = bs.settings["rebdates"][0]
    bs.build_selection(rebdate)

    out = bs.selection.filtered["ltr"]
    assert set(out.columns) == {"values", "binary"}
    assert out["binary"].sum() == 4
    # The learned scores must recover the planted skill ordering: the
    # top-4 selected should be mostly the truly-best assets.
    true_top = set(
        pd.Series(
            bs.data["return_series"].mean(), index=out.index
        ).nlargest(4).index
    )
    picked = set(out.index[out["binary"] == 1])
    assert len(picked & true_top) >= 3
    # And the selection machinery narrowed the universe accordingly.
    assert len(bs.selection.selected) == 4


def test_pairwise_loss_properties():
    """The RankNet loss must be zero-gradient-free at perfect ordering,
    penalize discordant pairs, and ignore masked slots."""
    import jax.numpy as jnp

    from porqua_tpu.models.ltr import pairwise_logistic_loss

    labels = jnp.asarray([2.0, 1.0, 0.0])
    mask = jnp.ones(3)
    good = pairwise_logistic_loss(jnp.asarray([3.0, 0.0, -3.0]), labels, mask)
    bad = pairwise_logistic_loss(jnp.asarray([-3.0, 0.0, 3.0]), labels, mask)
    assert float(good) < 0.1 < float(bad)

    # A masked slot with an absurd score must not change the loss.
    with_pad = pairwise_logistic_loss(
        jnp.asarray([3.0, 0.0, -3.0, 99.0]),
        jnp.asarray([2.0, 1.0, 0.0, 5.0]),
        jnp.asarray([1.0, 1.0, 1.0, 0.0]),
    )
    np.testing.assert_allclose(float(with_pad), float(good), rtol=1e-6)


def test_pairwise_ranker_ndcg_above_chance(rng):
    """VERDICT item 10: the JAX pairwise ranker must beat a chance
    ranking by NDCG@k on held-out cross-sections with a planted
    monotone signal."""
    import jax.numpy as jnp

    from porqua_tpu.models.lstm import ndcg
    from porqua_tpu.models.ltr import PairwiseRanker

    n_assets, n_feat, n_groups = 24, 5, 14
    truth = rng.standard_normal(n_feat)

    def make_group():
        X = rng.standard_normal((n_assets, n_feat)).astype(np.float32)
        signal = X @ truth
        y = signal + rng.standard_normal(n_assets) * 0.3
        ranks = y.argsort().argsort().astype(np.float32)  # 0..n-1 relevance
        return X, ranks

    groups = [make_group() for _ in range(n_groups)]
    model = PairwiseRanker(epochs=200, seed=1).fit(groups[:10])

    scores, rels = [], []
    for X, r in groups[10:]:
        scores.append(model.predict(X))
        rels.append(r)
    scores = np.stack(scores)
    rels = np.stack(rels)
    model_ndcg = float(np.mean(np.asarray(ndcg(
        jnp.asarray(scores), jnp.asarray(rels), k=5))))

    # Chance baseline: random score permutations on the same relevance.
    chance = []
    for _ in range(20):
        perm = np.stack([rng.permutation(n_assets).astype(float)
                         for _ in range(len(rels))])
        chance.append(float(np.mean(np.asarray(ndcg(
            jnp.asarray(perm), jnp.asarray(rels), k=5)))))
    assert model_ndcg > np.mean(chance) + 3 * np.std(chance), (
        model_ndcg, np.mean(chance), np.std(chance))
