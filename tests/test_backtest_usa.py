"""Golden-file regression for the large-universe (N=489) workflow.

Round-5 verdict item 7: the reference's ``example/backtest.ipynb``
workflow — a ~489-stock universe, monthly-style rebalances, selection
filter, turnover budget — exercised end-to-end through the real
strategy/batch engines (``BacktestService`` + ``Backtest.run`` and
``build_problems`` + ``solve_scan_turnover``), with weights and
simulated net returns pinned against a committed golden file.

Regenerate the golden (after an INTENTIONAL behavior change) with:
    python tests/test_backtest_usa.py --regen
"""
import os
import sys

# Direct-script (--regen) invocation: the package root is the parent
# directory, which script mode does not put on sys.path (pytest's
# conftest does).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd
import pytest

jnp = pytest.importorskip("jax.numpy")

from porqua_tpu import (
    Backtest,
    BacktestService,
    LeastSquares,
    OptimizationItemBuilder,
    SelectionItemBuilder,
)
from porqua_tpu.accounting import simulate_strategy
from porqua_tpu.batch import assemble_backtest, build_problems, solve_scan_turnover
from porqua_tpu.builders import (
    bibfn_bm_series,
    bibfn_box_constraints,
    bibfn_budget_constraint,
    bibfn_return_series,
    bibfn_selection_min_volume,
    bibfn_turnover_constraint,
)
from porqua_tpu.qp import SolverParams

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "backtest_usa.npz")

N_RAW, N_ADMIT = 520, 489
MIN_VOLUME = 1e6
WIDTH = 126
N_REB = 6
TURNOVER_BUDGET = 0.25


def _market():
    """520 raw assets, 489 liquid (the filter's admitted set is constant
    by construction so the positional scan carry is exact — names that
    exit mid-backtest are a serial-engine-only scenario, covered at
    small scale in test_batch_backtest.py)."""
    rng = np.random.default_rng(21)
    n_days = WIDTH + 21 * N_REB + 10
    dates = pd.bdate_range("2020-01-01", periods=n_days)
    k = 8
    B = 0.5 + 0.5 * rng.random((N_RAW, k))
    F = 0.008 * rng.standard_normal((n_days, k))
    eps = 0.01 * rng.standard_normal((n_days, N_RAW))
    X = pd.DataFrame(F @ B.T + eps, index=dates,
                     columns=[f"S{i:04d}" for i in range(N_RAW)])
    base = np.where(np.arange(N_RAW) < N_ADMIT, 10.0, 0.2) * MIN_VOLUME
    V = pd.DataFrame(
        base * rng.lognormal(sigma=0.3, size=(n_days, N_RAW)),
        index=dates, columns=X.columns)
    w = rng.dirichlet(np.ones(N_RAW) * 5.0)
    bm = pd.DataFrame({"SPTR": X.to_numpy() @ w}, index=dates)
    rebdates = [str(d.date()) for d in X.index[WIDTH::21][:N_REB]]
    return X, V, bm, rebdates


def _service(X, V, bm, rebdates):
    return BacktestService(
        data={"return_series": X, "bm_series": bm, "volume_series": V},
        selection_item_builders={
            "volume": SelectionItemBuilder(
                bibfn=bibfn_selection_min_volume, width=60,
                min_volume=MIN_VOLUME),
        },
        optimization_item_builders={
            "returns": OptimizationItemBuilder(
                bibfn=bibfn_return_series, width=WIDTH),
            "bm": OptimizationItemBuilder(
                bibfn=bibfn_bm_series, width=WIDTH, align=True),
            "budget": OptimizationItemBuilder(bibfn=bibfn_budget_constraint),
            "box": OptimizationItemBuilder(
                bibfn=bibfn_box_constraints, upper=0.05),
            "turnover": OptimizationItemBuilder(
                bibfn=bibfn_turnover_constraint,
                turnover_budget=TURNOVER_BUDGET),
        },
        # The ridge makes the rank-deficient (N > WIDTH) tracking
        # objective strongly convex so the serial/scan engines share a
        # unique optimum the golden can pin (see examples/backtest_usa.py).
        optimization=LeastSquares(dtype=jnp.float64, l2_penalty=1e-4),
        settings={"rebdates": rebdates, "quiet": True},
    )


TIGHT = SolverParams(eps_abs=1e-8, eps_rel=1e-8)


def _run_both():
    X, V, bm, rebdates = _market()

    probe = _service(X, V, bm, rebdates)
    probe.prepare_rebalancing(rebalancing_date=rebdates[0])
    universe = list(probe.optimization.constraints.selection)
    assert len(universe) == N_ADMIT  # the filter is doing the trimming
    w0 = {a: 1.0 / len(universe) for a in universe}

    bs_serial = _service(X, V, bm, rebdates)
    bs_serial.settings["prev_weights"] = dict(w0)
    bs_serial.optimization.params.update(TIGHT.__dict__)
    bt_serial = Backtest()
    bt_serial.run(bs_serial)

    bs_scan = _service(X, V, bm, rebdates)
    bs_scan.settings["prev_weights"] = dict(w0)
    problems = build_problems(bs_scan, dtype=jnp.float64)
    w_init = np.array([w0[a] for a in problems.universes[0]])
    sols = solve_scan_turnover(
        problems.qp, n_assets=len(problems.universes[0]), row_start=1,
        w_init=jnp.asarray(w_init), params=TIGHT,
        universes=problems.universes)
    bt_scan = assemble_backtest(problems, sols)

    sim = simulate_strategy(bt_scan.strategy, X, fc=0.0, vc=0.001)
    return X, rebdates, universe, w0, bt_serial, bt_scan, sim


@pytest.fixture(scope="module")
def usa_run():
    return _run_both()


def test_serial_and_scan_engines_agree(usa_run):
    # Tolerance: the l2 ridge's strong-convexity modulus is 2e-4, so a
    # ~1e-8-residual solve pins the weights only to ~residual/modulus
    # ~ 1e-4 — the engines agree to what the problem's conditioning
    # permits (measured max |dw| 1.1e-4), not to solver epsilon.
    _, rebdates, _, _, bt_serial, bt_scan, _ = usa_run
    for date in rebdates:
        ws = pd.Series(bt_serial.strategy.get_weights(date))
        wb = pd.Series(bt_scan.strategy.get_weights(date))
        np.testing.assert_allclose(wb[ws.index], ws, atol=5e-4,
                                   err_msg=date)


def test_turnover_budget_binds_the_chain(usa_run):
    _, rebdates, universe, w0, _, bt_scan, _ = usa_run
    prev = pd.Series(w0)
    for date in rebdates:
        cur = pd.Series(bt_scan.strategy.get_weights(date))
        spent = float((cur - prev.reindex(cur.index).fillna(0.0)).abs().sum())
        assert spent <= TURNOVER_BUDGET + 1e-6, (date, spent)
        prev = cur


def test_weights_and_net_returns_match_golden(usa_run):
    _, rebdates, _, _, _, bt_scan, sim = usa_run
    if not os.path.exists(GOLDEN):
        pytest.fail(f"golden file missing: {GOLDEN} — regenerate with "
                    f"`python {__file__} --regen`")
    g = np.load(GOLDEN, allow_pickle=False)
    w_first = pd.Series(bt_scan.strategy.get_weights(rebdates[0]))
    w_last = pd.Series(bt_scan.strategy.get_weights(rebdates[-1]))
    np.testing.assert_allclose(w_first.to_numpy(), g["w_first"], atol=2e-6)
    np.testing.assert_allclose(w_last.to_numpy(), g["w_last"], atol=2e-6)
    # Net returns are w . r, so the tolerance follows from the weight
    # slack above: ||dw||_1 <= 489 * 2e-6 ~ 1e-3 against ~1%-scale
    # daily returns bounds the drift by ~1e-5; 1e-6 holds with margin
    # on same-platform reruns while staying consistent with what the
    # weight checks permit.
    np.testing.assert_allclose(sim.to_numpy(), g["net_returns"], atol=1e-6)


def _regen():
    X, rebdates, universe, w0, bt_serial, bt_scan, sim = _run_both()
    os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
    np.savez_compressed(
        GOLDEN,
        w_first=pd.Series(bt_scan.strategy.get_weights(rebdates[0])).to_numpy(),
        w_last=pd.Series(bt_scan.strategy.get_weights(rebdates[-1])).to_numpy(),
        net_returns=sim.to_numpy(),
    )
    print(f"wrote {GOLDEN}")


if __name__ == "__main__":
    if "--regen" in sys.argv:
        import jax

        # Match the pytest conftest's numeric config exactly — the
        # golden must be regenerated under the settings it is checked
        # under.
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        _regen()
    else:
        print("usage: python tests/test_backtest_usa.py --regen")
