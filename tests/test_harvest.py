"""Telemetry warehouse + bench gate: harvest records, stage profiling,
histogram exposition, and the regression gate (ISSUE 7 acceptance)."""

import gzip
import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

import jax

from porqua_tpu.obs import (
    EventBus,
    HarvestSink,
    Observability,
    ObsHTTPServer,
    StageProfiler,
    load_harvest,
    prometheus_text,
    qp_solve_profile,
    solve_record,
)
from porqua_tpu.obs.harvest import (
    SCHEMA_VERSION,
    aggregate,
    harvest_solution,
)
from porqua_tpu.obs.profile import chrome_counter_events
from porqua_tpu.obs.report import harvest_section
from porqua_tpu.obs.rings import ring_history
from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.solve import SolverParams, solve_qp_batch
from porqua_tpu.serve.metrics import ServeMetrics

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def make_qp(n=6, m=2, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * n, n))
    P = A.T @ A / (2 * n) + np.eye(n)
    q = rng.standard_normal(n)
    C = np.concatenate([np.ones((1, n)), rng.standard_normal((m - 1, n))])
    return CanonicalQP.build(
        P, q, C=C, l=np.full(m, -1.0), u=np.ones(m),
        lb=np.zeros(n), ub=np.ones(n), dtype=dtype)


def stacked_batch(B=5, n=6, m=2, dtype=np.float32):
    return stack_qps([make_qp(n, m, seed=s, dtype=dtype)
                      for s in range(B)])


# ---------------------------------------------------------------------------
# HarvestSink
# ---------------------------------------------------------------------------

class TestHarvestSink:
    def test_jsonl_and_gzip_roundtrip(self, tmp_path):
        p = SolverParams(check_interval=25)
        for name in ("h.jsonl", "h.jsonl.gz"):
            path = str(tmp_path / name)
            with HarvestSink(path) as sink:
                for i in range(7):
                    sink.emit(solve_record("serve", 8, 2, 1, 50, 1e-6,
                                           1e-6, -1.0, params=p))
                assert sink.records == 7
                assert sink.write_failures == 0
            records = load_harvest(path)
            assert len(records) == 7
            assert records[0]["segments"] == 2  # ceil(50 / 25)
            assert records[0]["bucket"] == "8x2"
        # .gz really is gzip on disk.
        with gzip.open(str(tmp_path / "h.jsonl.gz"), "rt") as f:
            assert json.loads(f.readline())["source"] == "serve"

    def test_emit_never_raises_and_counts_failures(self, tmp_path):
        events = EventBus(capacity=16)
        path = str(tmp_path / "h.jsonl")
        sink = HarvestSink(path, events=events)
        sink.emit(solve_record("batch", 4, 1, 1, 10, 0.0, 0.0, 0.0))
        # Kill the underlying file handle: the next emit must not
        # raise, must count the failure, and later emits count drops.
        sink._sink.close()
        sink.emit(solve_record("batch", 4, 1, 1, 10, 0.0, 0.0, 0.0))
        assert sink.write_failures == 1
        sink.emit(solve_record("batch", 4, 1, 1, 10, 0.0, 0.0, 0.0))
        assert sink.dropped == 1
        assert sink.records == 3  # every emit counted
        assert events.events(kind="harvest_sink_failed")
        assert sink.counters() == {"harvest_records": 3,
                                   "harvest_write_failures": 1,
                                   "harvest_dropped": 1}
        sink.close()

    def test_unwritable_path_counts_not_raises(self, tmp_path):
        sink = HarvestSink(str(tmp_path / "nodir" / "h.jsonl"))
        assert sink.write_failures == 1
        sink.emit(solve_record("batch", 4, 1, 1, 10, 0.0, 0.0, 0.0))
        assert sink.records == 1 and sink.dropped == 1

    def test_in_memory_buffer_bounded(self):
        sink = HarvestSink(buffer_capacity=3)
        for i in range(5):
            sink.emit(solve_record("serve", 4, 1, 1, 10, 0.0, 0.0, 0.0))
        assert sink.records == 5
        assert len(sink.buffered()) == 3
        assert sink.dropped == 2

    def test_concurrent_emitters(self, tmp_path):
        path = str(tmp_path / "h.jsonl")
        sink = HarvestSink(path)
        p = SolverParams()

        def emitter(k):
            for i in range(50):
                sink.emit(solve_record("serve", 8, 2, 1, 25 * (k + 1),
                                       1e-6, 1e-6, 0.0, params=p))

        threads = [threading.Thread(target=emitter, args=(k,))
                   for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sink.close()
        records = load_harvest(path)
        assert len(records) == 400 and sink.records == 400
        # Interleaved writes never tore a line.
        assert all(r["v"] == SCHEMA_VERSION for r in records)


# ---------------------------------------------------------------------------
# producers
# ---------------------------------------------------------------------------

class TestBatchProducers:
    def test_harvest_disabled_is_bit_identical(self):
        """The acceptance pin: a harvested solve returns byte-for-byte
        the arrays an unharvested one does (harvest is host
        post-processing; the jaxpr half is contract GC105)."""
        from porqua_tpu.batch import BatchProblems, solve_batch

        params = SolverParams(max_iter=500, polish=False, ring_size=4)
        problems = BatchProblems(
            qp=stacked_batch(), rebdates=[str(i) for i in range(5)],
            universes=[["a"] * 6] * 5, n_assets_max=6)
        bare = solve_batch(problems, params)
        sink = HarvestSink()
        harvested = solve_batch(problems, params, harvest=sink)
        np.testing.assert_array_equal(np.asarray(bare.x),
                                      np.asarray(harvested.x))
        np.testing.assert_array_equal(np.asarray(bare.iters),
                                      np.asarray(harvested.iters))
        assert sink.records == 5

    def test_batch_records_match_solution(self):
        from porqua_tpu.batch import BatchProblems, solve_batch

        params = SolverParams(max_iter=500, polish=False, ring_size=8)
        problems = BatchProblems(
            qp=stacked_batch(), rebdates=[str(i) for i in range(5)],
            universes=[["a"] * 6] * 5, n_assets_max=6)
        sink = HarvestSink()
        sol = solve_batch(problems, params, harvest=sink)
        records = sink.buffered()
        # Record count == lanes the batch driver solved.
        assert len(records) == 5
        iters = np.asarray(sol.iters)
        prim = np.asarray(sol.prim_res)
        dual = np.asarray(sol.dual_res)
        for i, rec in enumerate(records):
            assert rec["source"] == "batch" and rec["lane"] == i
            assert rec["iters"] == int(iters[i])
            assert rec["eps_abs"] == params.eps_abs
            # The decoded ring's last sample IS the reported residual
            # (polish off -> bitwise, the rings pin).
            assert rec["ring"]["prim_res"][-1] == float(prim[i])
            assert rec["ring"]["dual_res"][-1] == float(dual[i])
            assert rec["ring"]["rho"][-1] > 0  # the rho trace rides along

    def test_compacted_records_carry_compaction_and_profile(self):
        from porqua_tpu.compaction import solve_batch_compacted

        params = SolverParams(max_iter=500, eps_abs=1e-6, eps_rel=1e-6,
                              polish=False, ring_size=4)
        sink = HarvestSink()
        sol, report = solve_batch_compacted(stacked_batch(), params,
                                            harvest=sink)
        records = sink.buffered()
        assert len(records) == 5
        for rec in records:
            assert rec["source"] == "batch.compacted"
            comp = rec["compaction"]
            assert comp["lane_segments"] == report.lane_segments
            assert comp["dense_lane_segments"] == report.dense_lane_segments
            prof = rec["profile"]
            assert prof["flops_est"] > 0 and prof["bytes_est"] > 0
            assert set(prof["stage_seconds"]) == {
                "init", "segment_step", "finalize"}
        # The report itself carries the same profile object.
        assert report.profile["batch"] == 5

    def test_scan_driver_harvest(self, tmp_path):
        from porqua_tpu.batch import FIXED_UNIVERSE
        from porqua_tpu.checkpoint import solve_scan_l1_checkpointed

        params = SolverParams(max_iter=500, polish=False, ring_size=4)
        sink = HarvestSink()
        sol, info = solve_scan_l1_checkpointed(
            stacked_batch(), 6, np.zeros(6), 0.001,
            str(tmp_path / "ckpt"), params=params, segment_size=2,
            harvest=sink, universes=FIXED_UNIVERSE)
        records = sink.buffered()
        assert len(records) == 5
        assert [r["lane"] for r in records] == list(range(5))
        assert all(r["source"] == "backtest.scan" for r in records)
        # Date 0 of a fresh run solved from the cold initial carry;
        # every later date chains the scan-carry warm start.
        assert records[0]["warm"] is False
        assert "warm_src" not in records[0]
        assert all(r["warm"] and r["warm_src"] == "scan_carry"
                   for r in records[1:])
        iters = np.asarray(sol.iters)
        for i, rec in enumerate(records):
            assert rec["iters"] == int(iters[i])
        # A resumed run re-harvests nothing (chunks already on disk).
        sink2 = HarvestSink()
        solve_scan_l1_checkpointed(
            stacked_batch(), 6, np.zeros(6), 0.001,
            str(tmp_path / "ckpt"), params=params, segment_size=2,
            harvest=sink2, universes=FIXED_UNIVERSE)
        assert sink2.records == 0


class TestServeProducer:
    def test_loadgen_harvest_reconciles_with_metrics(self, tmp_path):
        from porqua_tpu.serve.loadgen import (
            build_tracking_requests, run_loadgen)

        path = str(tmp_path / "harvest.jsonl.gz")
        requests = build_tracking_requests(40, n_assets=8, window=32)
        report = run_loadgen(requests, max_batch=16, ring_size=8,
                             harvest_out=path, warm_keys=True)
        assert report["errors"] == 0
        assert report["harvest_write_failures"] == 0
        # Measured-window record count == solves ServeMetrics observed.
        assert report["harvest_records_measured"] == 40
        records = load_harvest(path)
        assert len(records) == report["harvest_records"]
        by_trace = {r["trace_id"]: r for r in records}
        assert len(by_trace) == len(records)  # per-request identity
        for rec in records:
            assert rec["source"] == "serve"
            assert rec["n"] == 8
            assert rec["solve_s"] > 0 and rec["wall_s"] > 0
            # Final ring sample matches the reported residuals (AOT
            # serve path: within one f32 ulp — same bar as test_obs).
            assert rec["ring"]["prim_res"][-1] == pytest.approx(
                rec["prim_res"], rel=1e-5)
            assert rec["ring"]["dual_res"][-1] == pytest.approx(
                rec["dual_res"], rel=1e-5)
            prof = rec["profile"]
            assert prof["flops_est"] > 0 and prof["batch"] >= 1

    def test_harvest_out_external_service_raises(self):
        from porqua_tpu.serve import BucketLadder, SolveService
        from porqua_tpu.serve.loadgen import (
            build_tracking_requests, run_loadgen)

        svc = SolveService(params=SolverParams(max_iter=200, polish=False),
                           ladder=BucketLadder((8, 16), (4, 8)),
                           max_batch=4)
        reqs = build_tracking_requests(2, n_assets=8, window=16)
        with svc:
            with pytest.raises(ValueError, match="harvest_out"):
                run_loadgen(reqs, service=svc, harvest_out="/tmp/x.jsonl")

    def test_continuous_retirement_emits_segments(self):
        from porqua_tpu.serve import BucketLadder, SolveService

        params = SolverParams(max_iter=500, eps_abs=1e-5, eps_rel=1e-5,
                              polish=False, ring_size=4)
        sink = HarvestSink()
        profiler = StageProfiler()
        svc = SolveService(params=params,
                           ladder=BucketLadder((8, 16), (4, 8)),
                           max_batch=4, max_wait_ms=5.0,
                           continuous=True, harvest=sink,
                           profiler=profiler)
        with svc:
            results = [svc.solve(make_qp(seed=s), timeout=120)
                       for s in range(4)]
        assert all(r.found for r in results)
        records = sink.buffered()
        assert len(records) == 4
        iters_by_status = np.asarray([r.iters for r in results])
        for rec in records:
            assert rec["source"] == "serve.continuous"
            assert rec["segments"] >= 1
            assert rec["iters"] in iters_by_status
        stages = profiler.stage_seconds()
        assert {"serve/admit", "serve/segment_step",
                "serve/finalize"} <= set(stages)

    def test_warm_start_provenance(self):
        from porqua_tpu.serve import BucketLadder, SolveService

        params = SolverParams(max_iter=500, polish=False)
        sink = HarvestSink()
        svc = SolveService(params=params,
                           ladder=BucketLadder((8, 16), (4, 8)),
                           max_batch=4, max_wait_ms=2.0, harvest=sink)
        qp = make_qp(seed=3)
        with svc:
            svc.solve(qp, warm_key="book-1", timeout=120)
            svc.solve(qp, warm_key="book-1", timeout=120)
        recs = sink.buffered()
        assert len(recs) == 2
        # Cold first touch under an explicit key: warm False AND no
        # provenance — warm_src presence is the warm-membership key.
        assert recs[0]["warm"] is False
        assert "warm_src" not in recs[0]
        assert recs[1]["warm"] is True
        assert recs[1]["warm_src"] == "explicit"


# ---------------------------------------------------------------------------
# profiling
# ---------------------------------------------------------------------------

class TestProfile:
    def test_stage_profiler_and_counter_tracks(self):
        prof = StageProfiler()
        with prof.stage("segment_step"):
            pass
        with prof.stage("segment_step"):
            pass
        with prof.stage("finalize"):
            pass
        snap = prof.snapshot()
        assert snap["stages"]["segment_step"]["count"] == 2
        events = chrome_counter_events(prof, anchor_mono=0.0)
        assert len(events) == 3
        assert all(e["ph"] == "C" for e in events)
        names = {e["name"] for e in events}
        assert names == {"porqua/profile/segment_step",
                         "porqua/profile/finalize"}
        # Cumulative: the second segment_step sample >= the first.
        seg = [e["args"]["seconds"] for e in events
               if e["name"].endswith("segment_step")]
        assert seg[1] >= seg[0]

    def test_qp_solve_profile_fields(self):
        p = SolverParams(polish=False)
        prof = qp_solve_profile(500, 1, 25.0, 0.05, params=p, batch=252,
                                factor_rows=252,
                                device_kind="TPU v5 lite")
        assert prof["flops_est"] > 0 and prof["bytes_est"] > 0
        assert 0 < prof["mfu_bf16_peak"] < 1
        assert prof["roofline_bound"] in ("compute", "memory")
        # CPU device kinds have no known peaks: rates only, no MFU.
        prof_cpu = qp_solve_profile(16, 4, 50.0, 0.01, params=p)
        assert "mfu_bf16_peak" not in prof_cpu
        assert prof_cpu["achieved_tflops"] > 0

    def test_gc105_telemetry_identity_clean(self):
        from porqua_tpu.analysis import contracts

        assert contracts.check_telemetry_identity() == []


# ---------------------------------------------------------------------------
# exposition: histograms + obs counters
# ---------------------------------------------------------------------------

class TestExposition:
    def test_histogram_series_cumulative(self):
        m = ServeMetrics()
        for s in (0.0005, 0.002, 0.002, 0.03, 20.0):
            m.observe_latency(s)
        for it in (10, 60, 5000):
            m.observe_request_iters(it)
        text = prometheus_text(m.snapshot(), histograms=m.histograms())
        assert ("# TYPE porqua_serve_solve_latency_seconds histogram"
                in text)
        assert 'porqua_serve_solve_latency_seconds_bucket{le="0.001"} 1' \
            in text
        assert 'porqua_serve_solve_latency_seconds_bucket{le="0.0025"} 3' \
            in text
        assert 'porqua_serve_solve_latency_seconds_bucket{le="+Inf"} 5' \
            in text
        assert "porqua_serve_solve_latency_seconds_count 5" in text
        assert 'porqua_serve_lane_iterations_bucket{le="25"} 1' in text
        assert 'porqua_serve_lane_iterations_bucket{le="+Inf"} 3' in text
        # The percentile gauges stayed (backward compatibility).
        assert "porqua_serve_latency_p99_ms" in text
        # Sum is exact.
        h = m.histograms()["solve_latency_seconds"]
        assert h["sum"] == pytest.approx(20.0345)

    def test_histogram_series_custom_ladder(self):
        # latency_buckets is a deployment knob so SLO targets and
        # histogram edges align (ISSUE 9 satellite): the cumulative
        # series must follow the custom ladder exactly, default
        # untouched elsewhere.
        m = ServeMetrics(latency_buckets=(0.05, 0.25, 2.0))
        for s in (0.01, 0.1, 0.1, 1.0, 30.0):
            m.observe_latency(s)
        text = prometheus_text(m.snapshot(), histograms=m.histograms())
        assert 'porqua_serve_solve_latency_seconds_bucket{le="0.05"} 1' \
            in text
        assert 'porqua_serve_solve_latency_seconds_bucket{le="0.25"} 3' \
            in text
        assert 'porqua_serve_solve_latency_seconds_bucket{le="2"} 4' \
            in text
        assert 'porqua_serve_solve_latency_seconds_bucket{le="+Inf"} 5' \
            in text
        # The default ladder's edges must NOT appear.
        assert 'le="0.001"' not in text

    def test_extra_gauges_rendered(self):
        m = ServeMetrics()
        text = prometheus_text(
            m.snapshot(),
            extra_gauges={"slo_burn_rate_availability_fast_short": 2.5,
                          "slo_alert_state_availability_fast": 2})
        assert ("# TYPE porqua_serve_slo_burn_rate_availability_fast_"
                "short gauge" in text)
        assert "porqua_serve_slo_burn_rate_availability_fast_short 2.5" \
            in text
        assert "porqua_serve_slo_alert_state_availability_fast 2" in text

    def test_extra_counters_rendered(self):
        m = ServeMetrics()
        text = prometheus_text(
            m.snapshot(),
            extra_counters={"events_dropped": 3,
                            "harvest_write_failures": 1})
        assert "# TYPE porqua_serve_events_dropped counter" in text
        assert "porqua_serve_events_dropped 3" in text
        assert "porqua_serve_harvest_write_failures 1" in text

    def test_service_endpoint_histograms_and_healthz_loss_counters(
            self, tmp_path):
        from porqua_tpu.serve import BucketLadder, SolveService

        params = SolverParams(max_iter=200, polish=False)
        obs = Observability(event_capacity=2)
        sink = HarvestSink()
        svc = SolveService(params=params,
                           ladder=BucketLadder((8, 16), (4, 8)),
                           max_batch=4, obs=obs, harvest=sink)
        with svc:
            port = svc.start_http(0)
            svc.solve(make_qp(seed=11), timeout=120)
            # Saturate the tiny event bus so dropped > 0.
            for i in range(5):
                obs.events.emit("noise", "debug", i=i)
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            assert "porqua_serve_solve_latency_seconds_bucket" in text
            assert "porqua_serve_lane_iterations_bucket" in text
            assert "porqua_serve_events_dropped" in text
            assert "porqua_serve_harvest_records 1" in text
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10).read())
            assert health["ok"] is True
            assert health["events_dropped"] >= 1
            assert health["events_sink_failures"] == 0
            assert health["harvest_records"] == 1
            assert health["harvest_write_failures"] == 0

    def test_event_sink_failure_counted(self, tmp_path):
        bus = EventBus(capacity=8, path=str(tmp_path / "e.jsonl"))
        bus.emit("ok")
        bus._sink.close()  # simulate the disk dying under the stream
        bus.emit("after-death")
        assert bus.sink_failures == 1
        bus.emit("still-serving")
        assert bus.sink_failures == 1  # counted once; bus keeps working
        assert len(bus.events()) == 3

    @pytest.mark.slow
    def test_tsan_concurrent_scrapes_and_harvest(self, monkeypatch,
                                                 tmp_path):
        """GC008 thread roots: exposition handler threads + harvest
        emitters contend under PORQUA_TSAN=1 — lock discipline pinned
        at runtime (any inversion/foreign-release raises and fails
        the scrape or the emitter thread)."""
        monkeypatch.setenv("PORQUA_TSAN", "1")
        # Built AFTER setenv so every lock is a TSanLock.
        metrics = ServeMetrics()
        events = EventBus(capacity=64)
        sink = HarvestSink(str(tmp_path / "h.jsonl"), events=events)
        server = ObsHTTPServer(
            metrics_fn=lambda: prometheus_text(
                metrics.snapshot(), histograms=metrics.histograms(),
                extra_counters={"events_dropped": events.dropped,
                                **sink.counters()}),
            health_fn=lambda: {"ok": True, **sink.counters()})
        port = server.start()
        errors = []
        stop = threading.Event()
        p = SolverParams()

        def writer(k):
            try:
                i = 0
                while not stop.is_set():
                    metrics.observe_latency(0.001 * (k + 1))
                    metrics.observe_request_iters(25 * (k + 1))
                    sink.emit(solve_record("serve", 8, 2, 1, 25, 1e-6,
                                           1e-6, 0.0, params=p))
                    events.emit("tick", i=i)
                    i += 1
            except Exception as exc:  # noqa: BLE001 - the assertion
                errors.append(f"writer: {exc!r}")

        def scraper():
            try:
                for _ in range(20):
                    text = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=10).read().decode()
                    assert "_bucket" in text
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=10)
            except Exception as exc:  # noqa: BLE001 - the assertion
                errors.append(f"scraper: {exc!r}")

        writers = [threading.Thread(target=writer, args=(k,))
                   for k in range(3)]
        scrapers = [threading.Thread(target=scraper) for _ in range(3)]
        for t in writers + scrapers:
            t.start()
        for t in scrapers:
            t.join()
        stop.set()
        for t in writers:
            t.join()
        server.stop()
        sink.close()
        assert not errors, errors
        assert sink.records > 0 and sink.write_failures == 0


# ---------------------------------------------------------------------------
# aggregation + report section
# ---------------------------------------------------------------------------

class TestAggregate:
    def test_policy_table_groups(self):
        p1 = SolverParams(eps_abs=1e-3, eps_rel=1e-3)
        p2 = SolverParams(eps_abs=1e-5, eps_rel=1e-5)
        records = []
        for i in range(10):
            records.append(solve_record("serve", 24, 1, 1, 25, 1e-4,
                                        1e-4, 0.0, params=p1,
                                        bucket="32x4", warm=i < 5))
        for i in range(4):
            records.append(solve_record("batch", 24, 1, 1,
                                        100 if i < 3 else 400,
                                        1e-6, 1e-6, 0.0, params=p2,
                                        bucket="32x4"))
        agg = aggregate(records)
        assert agg["records"] == 14
        assert agg["sources"] == {"serve": 10, "batch": 4}
        assert len(agg["groups"]) == 2
        tight = next(g for g in agg["groups"] if g["eps_abs"] == 1e-5)
        # 3 lanes at 4 segments + 1 at 16: wasted = 1 - 28/64.
        assert tight["wasted_iteration_fraction"] == pytest.approx(
            1 - 28 / 64)
        loose = next(g for g in agg["groups"] if g["eps_abs"] == 1e-3)
        assert loose["warm_count"] == 5 and loose["cold_count"] == 5

    def test_harvest_section_renders(self):
        p = SolverParams()
        records = [solve_record(
            "serve", 8, 2, 1, 50, 1e-6, 1e-7, 0.0, params=p,
            trace_id=f"t{i}",
            ring={"iters": [25, 50], "prim_res": [1e-3, 1e-6],
                  "dual_res": [1e-4, 1e-7], "rho": [0.1, 0.1]})
            for i in range(3)]
        text = harvest_section(records)
        assert "solved: 3 trajectories" in text
        assert "wasted-iteration attribution" in text
        assert "t0" in text
        assert harvest_section([]) == "harvest: (no records)"


# ---------------------------------------------------------------------------
# bench gate
# ---------------------------------------------------------------------------

class TestBenchGate:
    @pytest.fixture()
    def gate(self):
        sys.path.insert(0, _SCRIPTS)
        try:
            import bench_gate
        finally:
            sys.path.remove(_SCRIPTS)
        return bench_gate

    def test_selftest_passes(self, gate):
        assert gate._selftest() == 0

    def test_pass_and_fail_verdicts(self, gate):
        base = gate._synthetic_baseline()
        good = json.loads(json.dumps(base))
        good["value"] *= 1.1
        verdict = gate.check_payload(base, good)
        assert verdict["ok"] and verdict["n_fail"] == 0
        bad = json.loads(json.dumps(base))
        bad["config_compaction"]["te_drift"] = 1e-2
        bad["iters_p95"] = base["iters_p95"] * 2
        verdict = gate.check_payload(base, bad)
        assert not verdict["ok"]
        assert set(verdict["failed"]) == {"compaction_te_parity",
                                          "iters_p95"}

    def test_r05_artifact_gates_clean_against_itself(self, gate):
        r05 = os.path.join(os.path.dirname(_SCRIPTS), "BENCH_r05.json")
        payload = gate.load_payload(r05)
        assert "value" in payload  # the wrapper's parsed form
        verdict = gate.check_payload(payload, payload)
        assert verdict["ok"], verdict["failed"]
        # Metrics the r05 artifact predates are skipped, not failed.
        assert verdict["n_skip"] > 0

    def test_tolerance_scale(self, gate):
        base = gate._synthetic_baseline()
        cand = json.loads(json.dumps(base))
        cand["vs_baseline"] *= 0.75  # inside 0.7x floor, outside 0.94x
        assert gate.check_payload(base, cand)["ok"]
        strict = gate.check_payload(base, cand, tolerance_scale=0.2)
        assert not strict["ok"] and "headline_speedup" in strict["failed"]

    def test_verdict_json_written(self, gate, tmp_path):
        base = gate._synthetic_baseline()
        bpath, cpath = tmp_path / "b.json", tmp_path / "c.json"
        bpath.write_text(json.dumps(base))
        cpath.write_text(json.dumps(base))
        out = tmp_path / "verdict.json"
        # Drive the CLI via argv.
        argv = sys.argv
        sys.argv = ["bench_gate.py", "--baseline", str(bpath),
                    "--payload", str(cpath), "--out", str(out)]
        try:
            rc = gate.main()
        finally:
            sys.argv = argv
        assert rc == 0
        verdict = json.loads(out.read_text())
        assert verdict["ok"] and verdict["baseline_path"] == str(bpath)
