"""Constraints DSL shape contract + canonical lowering equivalence.

Port of the reference's only pure unit test
(``test/tests_quadratic_program.py:28-58``): budget + box + mixed-sense
linear rows on a 24-asset universe, asserting exact ``to_GhAb`` output
shapes with and without box-to-G folding — plus checks the reference
never had: row *content* (sign flips), the interval-form lowering, and
L1 recording.
"""

import numpy as np
import pandas as pd
import pytest

from porqua_tpu.constraints import Constraints, box_constraint, match_arg


@pytest.fixture
def universe():
    return [f"A{i:02d}" for i in range(24)]


@pytest.fixture
def cons(universe):
    c = Constraints(selection=universe)
    c.add_budget()                       # sum w  = 1      -> A row
    c.add_box("LongOnly", upper=0.2)     # 0 <= w <= 0.2   -> lb/ub
    n = len(universe)
    A = pd.DataFrame(
        np.vstack([np.eye(n)[0], np.eye(n)[1], np.eye(n)[2],
                   np.ones(n), np.eye(n)[5]]),
        columns=universe,
    )
    c.add_linear(
        Amat=A,
        sense=pd.Series(["=", "=", "=", "<=", ">="]),
        rhs=pd.Series([0.1, 0.1, 0.05, 1.0, 0.01]),
    )
    return c


def test_to_GhAb_shapes(cons, universe):
    n = len(universe)
    out = cons.to_GhAb()
    # budget(=) + three linear '=' rows -> A: (4, N)
    assert out["A"].shape == (4, n)
    assert out["b"].shape == (4,)
    # one '<=' + one '>=' (flipped) -> G: (2, N)
    assert out["G"].shape == (2, n)
    assert out["h"].shape == (2,)


def test_to_GhAb_box_folding(cons, universe):
    n = len(universe)
    out = cons.to_GhAb(lbub_to_G=True)
    # [-I; I] box rows prepend the linear inequality rows
    assert out["G"].shape == (2 + 2 * n, n)
    np.testing.assert_allclose(out["h"][:n], 0.0)          # -lb
    np.testing.assert_allclose(out["h"][n:2 * n], 0.2)     # ub


def test_geq_rows_are_sign_flipped(cons):
    out = cons.to_GhAb()
    # Last G row came from 'w5 >= 0.01' -> '-w5 <= -0.01'
    assert out["h"][-1] == pytest.approx(-0.01)
    assert out["G"][-1].sum() == pytest.approx(-1.0)


def test_canonical_interval_equivalence(cons, universe):
    """to_canonical must encode exactly the same polytope: eq rows get
    l == u, one-sided ineq rows keep exactly one infinite bound (a
    ``>=`` row may stay as a finite-lower/infinite-upper interval — no
    sign flip is required in interval form)."""
    n = len(universe)
    qp = cons.to_canonical()
    assert qp.n == n
    assert qp.m == 6  # 4 eq + 2 ineq
    l, u = np.asarray(qp.l), np.asarray(qp.u)
    np.testing.assert_allclose(l[:4], u[:4])
    assert np.all(np.isinf(l[4:]) != np.isinf(u[4:]))
    # The 'w5 >= 0.01' row must appear with its original orientation
    # preserved up to sign: either (0.01 <= w5) or (-w5 <= -0.01).
    C = np.asarray(qp.C)
    row = next(i for i in range(4, 6) if abs(C[i, 5]) == 1.0 and
               abs(C[i]).sum() == 1.0)
    bound = l[row] if C[row, 5] > 0 else -u[row]
    assert bound == pytest.approx(0.01)
    np.testing.assert_allclose(np.asarray(qp.lb), 0.0)
    np.testing.assert_allclose(np.asarray(qp.ub), 0.2)


def test_budget_overwrite_warns(universe):
    c = Constraints(selection=universe)
    c.add_budget()
    with pytest.warns(UserWarning):
        c.add_budget(rhs=2)
    assert c.budget["rhs"] == 2


def test_box_validation():
    assert box_constraint("Unbounded")["lower"] == -np.inf
    assert box_constraint("LongShort")["lower"] == -1
    with pytest.raises(ValueError):
        box_constraint("LongOnly", lower=[-0.5, 0.0])


def test_match_arg_partial():
    assert match_arg("Long", ["LongOnly", "Unbounded"]) == "LongOnly"
    with pytest.raises(ValueError):
        match_arg("Short", ["LongOnly"])


def test_add_linear_via_a_values(universe):
    c = Constraints(selection=universe)
    c.add_linear(a_values=pd.Series({"A00": 1.0, "A05": -1.0}),
                 sense="<=", rhs=0.0, name="spread")
    out = c.to_GhAb()
    assert out["G"].shape == (1, len(universe))
    assert out["G"][0, 0] == 1.0 and out["G"][0, 5] == -1.0
    # Unnamed assets fill with zeros
    assert out["G"][0, 1] == 0.0


def test_add_l1_records(universe):
    c = Constraints(selection=universe)
    c.add_l1("turnover", rhs=0.5, x0={"A00": 1.0})
    assert c.l1["turnover"]["rhs"] == 0.5
    with pytest.raises(TypeError):
        c.add_l1("leverage")
