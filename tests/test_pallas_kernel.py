"""Parity tests: Pallas fused ADMM segment vs the stock XLA path.

The Pallas kernel (``porqua_tpu/ops/admm_kernel.py``) must be
bit-for-algorithm equivalent to ``admm_solve``'s in-line iteration: same
splitting, same updates, same certificates. These tests pin that by
running both backends on identical problems (interpret mode on CPU) and
comparing states, solutions, and solve-quality metrics — the same
methodology as the reference's cross-solver harness
(``example/compare_solver.ipynb`` cells 6/8/12).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from porqua_tpu.qp.admm import SolverParams
from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.solve import solve_qp, solve_qp_batch
from porqua_tpu.tracking import build_tracking_qp


def random_qp(rng, n=16, m=5, dtype=np.float64):
    """Random strongly-convex QP with mixed eq/ineq rows and a box."""
    A = rng.standard_normal((n, n))
    P = A @ A.T + 0.1 * np.eye(n)
    q = rng.standard_normal(n)
    C = rng.standard_normal((m, n))
    # First row equality (budget-like), rest two-sided intervals.
    l = np.concatenate([[1.0], -np.abs(rng.standard_normal(m - 1)) - 0.5])
    u = np.concatenate([[1.0], np.abs(rng.standard_normal(m - 1)) + 0.5])
    lb = np.full(n, -2.0)
    ub = np.full(n, 2.0)
    return CanonicalQP.build(P, q, C, l, u, lb, ub, dtype=dtype)


PARAMS_XLA = SolverParams(backend="xla", max_iter=2000)
PARAMS_PALLAS = SolverParams(backend="pallas", max_iter=2000)


class TestSegmentParity:
    def test_solution_parity_random_qps(self, rng):
        for i in range(4):
            qp = random_qp(rng, n=8 + 4 * i, m=3 + i)
            ref = solve_qp(qp, PARAMS_XLA)
            pal = solve_qp(qp, PARAMS_PALLAS)
            assert int(pal.status) == int(ref.status)
            np.testing.assert_allclose(
                np.asarray(pal.x), np.asarray(ref.x), atol=1e-6, rtol=1e-5
            )
            np.testing.assert_allclose(
                float(pal.obj_val), float(ref.obj_val), rtol=1e-6, atol=1e-8
            )

    def test_residuals_meet_tolerance(self, rng):
        qp = random_qp(rng, n=24, m=6)
        sol = solve_qp(qp, PARAMS_PALLAS)
        assert bool(sol.found)
        assert float(sol.prim_res) <= 1e-5
        assert float(sol.dual_res) <= 1e-5

    def test_tracking_qp_parity(self, rng):
        X = jnp.asarray(rng.standard_normal((64, 24)) * 0.01)
        y = jnp.asarray(np.asarray(X) @ (np.ones(24) / 24))
        qp = build_tracking_qp(X.astype(jnp.float64), y.astype(jnp.float64))
        ref = solve_qp(qp, PARAMS_XLA)
        pal = solve_qp(qp, PARAMS_PALLAS)
        assert bool(pal.found)
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=1e-6
        )
        # Budget and box hold.
        assert abs(float(jnp.sum(pal.x)) - 1.0) < 1e-6
        assert float(jnp.min(pal.x)) >= -1e-7

    def test_vmap_batch(self, rng):
        """pallas_call must batch correctly under vmap (grid axis)."""
        qps = stack_qps([random_qp(rng, n=12, m=4) for _ in range(3)])
        ref = solve_qp_batch(qps, PARAMS_XLA)
        pal = solve_qp_batch(qps, PARAMS_PALLAS)
        np.testing.assert_array_equal(
            np.asarray(pal.status), np.asarray(ref.status)
        )
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=1e-6, rtol=1e-5
        )

    def test_infeasible_detection(self):
        """Contradictory rows must still yield an infeasibility certificate."""
        n = 6
        P = np.eye(n)
        q = np.zeros(n)
        C = np.vstack([np.ones(n), np.ones(n)])
        l = np.array([1.0, -np.inf])
        u = np.array([1.0, -1.0])  # sum(x) == 1 and sum(x) <= -1
        qp = CanonicalQP.build(P, q, C, l, u, np.full(n, -5.0), np.full(n, 5.0),
                               dtype=np.float64)
        sol = solve_qp(qp, PARAMS_PALLAS)
        assert not bool(sol.found)

    def test_float32(self, rng):
        """The TPU dtype path (f32) agrees with f64 to f32 tolerances."""
        qp64 = random_qp(rng, n=16, m=5, dtype=np.float64)
        qp32 = jax.tree.map(lambda a: a.astype(jnp.float32), qp64)
        p32 = SolverParams(backend="pallas", eps_abs=1e-5, eps_rel=1e-5)
        ref = solve_qp(qp64, PARAMS_XLA)
        pal = solve_qp(qp32, p32)
        assert bool(pal.found)
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=5e-4
        )


class TestTriangularKernel:
    """The trinv variant of the fused segment: L^-1 VMEM-resident and
    applied twice (K^-1 = L^-T L^-1), matching the XLA trinv path's
    accuracy story inside the kernel."""

    def test_trinv_kernel_matches_xla(self, rng):
        qp = random_qp(rng, n=20, m=6, dtype=np.float64)
        ref = solve_qp(qp, SolverParams(
            backend="xla", linsolve="trinv",
            eps_abs=1e-8, eps_rel=1e-8, max_iter=20000))
        pal = solve_qp(qp, SolverParams(
            backend="pallas", linsolve="trinv",
            eps_abs=1e-8, eps_rel=1e-8, max_iter=20000))
        assert bool(pal.found)
        # Interpret mode runs the identical arithmetic: exact agreement.
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=1e-9)
        np.testing.assert_array_equal(
            np.asarray(pal.iters), np.asarray(ref.iters))

    def test_trinv_kernel_l1(self, rng):
        """Native L1 prox inside the trinv kernel."""
        qp = random_qp(rng, n=12, m=3, dtype=np.float64)
        n = qp.n
        kw = dict(l1_weight=jnp.full(n, 1e-3, jnp.float64),
                  l1_center=jnp.zeros(n, jnp.float64))
        ref = solve_qp(qp, SolverParams(
            backend="xla", linsolve="trinv",
            eps_abs=1e-8, eps_rel=1e-8, max_iter=20000), **kw)
        pal = solve_qp(qp, SolverParams(
            backend="pallas", linsolve="trinv",
            eps_abs=1e-8, eps_rel=1e-8, max_iter=20000), **kw)
        assert bool(pal.found)
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=1e-9)

    def test_trinv_kernel_vmap_f32(self, rng):
        """The TPU-default variant (trinv) under the batch/grid lowering
        and the f32 dtype it actually runs with on hardware."""
        from porqua_tpu.qp.canonical import stack_qps
        from porqua_tpu.qp.solve import solve_qp_batch

        qps64 = [random_qp(rng, n=14, m=4, dtype=np.float64)
                 for _ in range(5)]
        batch32 = jax.tree.map(
            lambda a: a.astype(jnp.float32), stack_qps(qps64))
        p = SolverParams(backend="pallas", linsolve="trinv",
                         eps_abs=1e-5, eps_rel=1e-5, max_iter=4000)
        pal = solve_qp_batch(batch32, p)
        ref64 = [solve_qp(q, PARAMS_XLA) for q in qps64]
        for i, r in enumerate(ref64):
            assert int(pal.status[i]) == 1
            np.testing.assert_allclose(
                np.asarray(pal.x[i]), np.asarray(r.x), atol=5e-4)


class TestFactoredKernel:
    """Round-4 factored (capacitance/Woodbury) fused segment: the
    resident operator is (W, inv_d, Y0, Ginv) instead of any n x n
    array — the kernel form matching the promoted TPU headline config
    (linsolve="woodbury", refine 0). Parity reference is the XLA
    woodbury path on the SAME problems."""

    def _tracking_qp(self, rng, T=48, n=20, dtype=jnp.float64):
        X = jnp.asarray(rng.standard_normal((T, n)) * 0.01, dtype)
        y = jnp.asarray(np.asarray(X) @ (np.ones(n) / n), dtype)
        return build_tracking_qp(X, y)

    def test_factored_kernel_matches_xla_woodbury(self, rng):
        qp = self._tracking_qp(rng)
        kw = dict(linsolve="woodbury", woodbury_refine=0,
                  eps_abs=1e-8, eps_rel=1e-8, max_iter=20000)
        ref = solve_qp(qp, SolverParams(backend="xla", **kw))
        pal = solve_qp(qp, SolverParams(backend="pallas", **kw))
        assert bool(pal.found)
        # The only arithmetic difference vs XLA is the m x m row-Schur
        # solve (explicit Ginv in-kernel vs LU per iteration) — atol
        # covers that, far below solver eps.
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=1e-9)
        np.testing.assert_array_equal(
            np.asarray(pal.iters), np.asarray(ref.iters))

    def test_factored_kernel_l1(self, rng):
        """Native L1 prox (turnover-cost path) inside the factored
        kernel."""
        qp = self._tracking_qp(rng, T=32, n=12)
        n = qp.n
        kw = dict(l1_weight=jnp.full(n, 1e-3, jnp.float64),
                  l1_center=jnp.full(n, 1.0 / n, jnp.float64))
        sp = dict(linsolve="woodbury", woodbury_refine=0,
                  eps_abs=1e-8, eps_rel=1e-8, max_iter=20000)
        ref = solve_qp(qp, SolverParams(backend="xla", **sp), **kw)
        pal = solve_qp(qp, SolverParams(backend="pallas", **sp), **kw)
        assert bool(pal.found)
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=1e-9)

    def test_factored_kernel_vmap_f32_headline_config(self, rng):
        """The promoted TPU headline config (woodbury, refine 0,
        check_interval 35, f32, loose eps) under the batch/grid
        lowering — small shapes, exact same solver settings."""
        from porqua_tpu.qp.canonical import stack_qps
        from porqua_tpu.qp.solve import solve_qp_batch

        qps = stack_qps([self._tracking_qp(rng, T=40, n=16,
                                           dtype=jnp.float32)
                         for _ in range(4)])
        kw = dict(linsolve="woodbury", woodbury_refine=0,
                  check_interval=35, eps_abs=1e-3, eps_rel=1e-3,
                  polish=False, scaling_iters=2, max_iter=2000)
        ref = solve_qp_batch(qps, SolverParams(backend="xla", **kw))
        pal = solve_qp_batch(qps, SolverParams(backend="pallas", **kw))
        assert np.all(np.asarray(pal.status) == 1)
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=5e-6)

    def test_factored_kernel_refine1_matches_xla(self, rng):
        """The library-default accuracy mode (woodbury_refine=1): the
        in-kernel iterative refinement (V, Dv resident) must reproduce
        the XLA path's refined apply exactly."""
        qp = self._tracking_qp(rng, T=40, n=16)
        kw = dict(linsolve="woodbury", woodbury_refine=1,
                  eps_abs=1e-8, eps_rel=1e-8, max_iter=20000)
        ref = solve_qp(qp, SolverParams(backend="xla", **kw))
        pal = solve_qp(qp, SolverParams(backend="pallas", **kw))
        assert bool(pal.found)
        np.testing.assert_allclose(
            np.asarray(pal.x), np.asarray(ref.x), atol=1e-9)
        np.testing.assert_array_equal(
            np.asarray(pal.iters), np.asarray(ref.iters))
