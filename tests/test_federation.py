"""Fleet telemetry federation, process vitals, and the run ledger
(ISSUE 13 acceptance): collector merge/reconciliation/liveness, the
worker-crash incident cell, bounded rollups, vitals leak trending,
ledger trend gating, the single-service vitals gauges, and GC108."""

import json
import os
import sys

import pytest

from porqua_tpu.obs import (
    FleetCollector,
    FlightRecorder,
    SLOEngine,
    VitalsTrend,
    WorkerStream,
    default_slos,
    process_vitals,
)
from porqua_tpu.obs import ledger
from porqua_tpu.obs.report import fleet_section
from porqua_tpu.resilience.faults import FaultClock, FaultSpec

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def slo_sample(completed, failed=0, counts=(0, 0), le=(0.01, 0.1),
               expired=0):
    """A synthetic cumulative ServeMetrics.slo_sample() payload."""
    counts = tuple(counts) + (0,) * (len(le) + 1 - len(counts))
    return {"completed": completed, "failed": failed,
            "expired": expired, "retry_giveups": 0,
            "validation_failures": 0, "latency_le": tuple(le),
            "latency_counts": counts, "latency_count": sum(counts)}


def make_fleet(tmp_path, workers=("w0", "w1"), clock=None, **kwargs):
    clock = FaultClock() if clock is None else clock
    kwargs.setdefault("heartbeat_timeout_s", 5.0)
    kwargs.setdefault("rollup_window_s", 2.0)
    col = FleetCollector(clock=clock, **kwargs)
    streams = {}
    for wid in workers:
        path = str(tmp_path / f"{wid}.jsonl")
        col.add_worker(wid, path)
        streams[wid] = WorkerStream(path, wid)
        streams[wid].hello(latency_le=[0.01, 0.1])
    return col, streams, clock


# ---------------------------------------------------------------------------
# collector: merge / namespacing / robustness
# ---------------------------------------------------------------------------

class TestCollectorMerge:
    def test_counters_and_raw_histograms_sum(self, tmp_path):
        col, streams, _ = make_fleet(tmp_path)
        streams["w0"].sample(slo_sample(10, failed=1, counts=(6, 4, 1)),
                             hist={"solve_latency_seconds": {
                                 "le": (0.01, 0.1), "counts": [6, 4, 1],
                                 "sum": 0.4, "count": 11}})
        streams["w1"].sample(slo_sample(20, counts=(15, 5, 0)),
                             hist={"solve_latency_seconds": {
                                 "le": (0.01, 0.1), "counts": [15, 5, 0],
                                 "sum": 0.2, "count": 20}})
        col.drain()
        merged = col.slo_sample()
        assert merged["completed"] == 30
        assert merged["failed"] == 1
        # RAW bucket counts merge element-wise — never percentiles.
        assert merged["latency_counts"] == (21, 9, 1)
        assert merged["latency_count"] == 31
        hist = col.histograms()["solve_latency_seconds"]
        assert hist["counts"] == [21, 9, 1]
        assert hist["count"] == 31
        assert abs(hist["sum"] - 0.6) < 1e-12

    def test_cumulative_samples_replace_not_accumulate(self, tmp_path):
        col, streams, _ = make_fleet(tmp_path, workers=("w0",))
        streams["w0"].sample(slo_sample(10))
        col.drain()
        streams["w0"].sample(slo_sample(25))
        streams["w0"].sample(slo_sample(40))
        col.drain()
        # Latest cumulative wins; draining twice must not double-count.
        assert col.slo_sample()["completed"] == 40
        col.drain()
        assert col.slo_sample()["completed"] == 40

    def test_trace_ids_namespaced_by_worker(self, tmp_path):
        col, streams, _ = make_fleet(tmp_path)
        for wid in ("w0", "w1"):
            streams[wid].event({"kind": "backpressure_reject",
                                "severity": "warn", "trace_id": "t17"})
        col.drain()
        ids = sorted(e["trace_id"]
                     for e in col.events.events("backpressure_reject"))
        assert ids == ["w0/t17", "w1/t17"]
        workers = {e["worker"]
                   for e in col.events.events("backpressure_reject")}
        assert workers == {"w0", "w1"}

    def test_partial_trailing_line_not_consumed(self, tmp_path):
        col, streams, _ = make_fleet(tmp_path, workers=("w0",))
        streams["w0"].sample(slo_sample(5))
        col.drain()
        with open(streams["w0"].path, "a") as f:
            f.write('{"t": 1, "w": "w0", "kind": "sample", "slo"')
        col.drain()
        assert col.counters()["fleet_parse_errors"] == 0
        assert col.slo_sample()["completed"] == 5
        with open(streams["w0"].path, "a") as f:
            f.write(': %s}\n' % json.dumps(slo_sample(9)))
        col.drain()
        assert col.slo_sample()["completed"] == 9

    def test_garbage_line_counted_not_fatal(self, tmp_path):
        col, streams, _ = make_fleet(tmp_path, workers=("w0",))
        with open(streams["w0"].path, "a") as f:
            f.write("not json at all\n")
        streams["w0"].sample(slo_sample(3))
        col.drain()
        assert col.counters()["fleet_parse_errors"] == 1
        assert col.slo_sample()["completed"] == 3

    def test_mismatched_histogram_ladder_refused(self, tmp_path):
        col = FleetCollector(clock=FaultClock())
        streams = {}
        for wid, le in (("a", [0.01, 0.1]), ("b", [0.02, 0.2])):
            path = str(tmp_path / f"{wid}.jsonl")
            col.add_worker(wid, path)
            streams[wid] = WorkerStream(path, wid)
            streams[wid].hello(latency_le=le)
        streams["a"].sample(slo_sample(5, counts=(5, 0)))
        with pytest.raises(ValueError, match="ladder"):
            col.drain()
        # The refusal is STICKY: a supervisor that catches the error
        # and keeps polling must never see the mismatched worker's
        # buckets summed against the fleet ladder — its samples are
        # excluded from every merge surface, the error fires once, and
        # the same-round records of the well-behaved worker landed.
        assert col.slo_sample()["completed"] == 5
        streams["b"].sample(slo_sample(99, counts=(90, 9)))
        col.drain()  # no re-raise
        assert col.slo_sample()["completed"] == 5
        assert col.slo_sample()["latency_counts"] == (5, 0, 0)
        assert col.counters()["fleet_ladder_refusals"] == 1
        report = col.report()
        statuses = {r["worker"]: r["status"] for r in report["rows"]}
        assert statuses["b"] == "refused"
        assert report["fleet"]["completed"] == 5
        assert report["reconciled"], report["reconciliation"]
        for s in streams.values():
            s.close()

    def test_fleet_throughput_sums_worker_measured_rates(self, tmp_path):
        # Each worker times exactly its own measured soak window; the
        # fleet rate is their sum. Collector lifetime (which starts
        # before spawn + prewarm + warmup) must NOT be the denominator
        # — that number deflates with host compile speed and would
        # poison the trend-gated ledger series.
        col, streams, clock = make_fleet(tmp_path)
        clock.advance(300.0)  # a long prewarm before any completion
        for wid in ("w0", "w1"):
            streams[wid].sample(slo_sample(1200, counts=(1200, 0)))
            streams[wid].report({
                "completed": 1200, "failed": 0, "harvest_records": 1200,
                "throughput_solves_per_s": 120.0, "duration_s": 10.0})
        col.drain()
        report = col.report()
        assert report["fleet"]["throughput_solves_per_s"] == 240.0
        assert report["reconciled"]

    def test_mean_shaped_snap_keys_average_not_sum(self, tmp_path):
        col, streams, _ = make_fleet(tmp_path)
        for wid in ("w0", "w1"):
            streams[wid].sample(slo_sample(10),
                                snap={"occupancy_mean": 0.8,
                                      "submitted": 10})
        col.drain()
        snap = col.snapshot()
        # 2 workers at 0.8 occupancy are a fleet at 0.8, not 1.6 —
        # while count-shaped keys still sum.
        assert abs(snap["occupancy_mean"] - 0.8) < 1e-12
        assert snap["submitted"] == 20.0

    def test_dead_worker_vitals_leave_rollups_and_gauges(self, tmp_path):
        col, streams, clock = make_fleet(tmp_path, rollup_window_s=2.0)
        for wid in ("w0", "w1"):
            streams[wid].sample(slo_sample(10),
                                vitals={"rss_bytes": 5e8, "open_fds": 9,
                                        "threads": 3, "queue_depth": 0})
        clock.advance(2.0)
        col.drain()
        assert col.rollups()[-1]["rss_sum_bytes"] == 1e9
        # w1 dies; its pre-crash RSS must not inflate later windows,
        # and its frozen vitals must leave the live gauges (worker_up
        # already says why) — the row keeps them for forensics.
        clock.advance(6.0)
        streams["w0"].sample(slo_sample(20),
                             vitals={"rss_bytes": 5e8, "open_fds": 9,
                                     "threads": 3, "queue_depth": 0})
        col.drain()
        assert col.worker_rows()[1]["status"] == "lost"
        clock.advance(2.0)
        col.drain()
        assert col.rollups()[-1]["rss_sum_bytes"] == 5e8
        gauges = col.worker_gauges()
        assert [lbl["worker"] for lbl, _ in gauges["worker_rss_bytes"]] \
            == ["w0"]
        ups = {lbl["worker"]: v for lbl, v in gauges["worker_up"]}
        assert ups == {"w0": 1.0, "w1": 0.0}
        assert "vitals" in col.worker_rows()[1]

    def test_stalled_poll_rollup_row_carries_true_span(self, tmp_path):
        col, streams, clock = make_fleet(tmp_path, workers=("w0",),
                                         rollup_window_s=2.0)
        streams["w0"].sample(slo_sample(10))
        clock.advance(2.0)
        col.drain()
        # The driver stalls for 3 windows; the single catch-up row
        # must say it spans them, or rates derived from rollups spike.
        streams["w0"].sample(slo_sample(70))
        clock.advance(6.0)
        col.drain()
        rolls = col.rollups()
        assert rolls[-1]["span_s"] == 6.0
        assert rolls[-1]["completed"] == 60.0
        assert rolls[0]["span_s"] == 2.0

    def test_duplicate_worker_refused(self, tmp_path):
        col = FleetCollector(clock=FaultClock())
        col.add_worker("w0", str(tmp_path / "w0.jsonl"))
        with pytest.raises(ValueError, match="already registered"):
            col.add_worker("w0", str(tmp_path / "other.jsonl"))


# ---------------------------------------------------------------------------
# liveness + the worker-crash incident cell
# ---------------------------------------------------------------------------

class TestLiveness:
    def test_stale_worker_fires_exactly_one_worker_lost_bundle(
            self, tmp_path):
        clock = FaultClock()
        flight = FlightRecorder(out_dir=None, debounce_s=0.0,
                                clock=clock)
        col, streams, _ = make_fleet(tmp_path, clock=clock,
                                     flight=flight)
        streams["w0"].sample(slo_sample(10))
        streams["w1"].sample(slo_sample(10))
        col.drain()
        # w0 goes silent; w1 keeps heartbeating past the deadline.
        for i in range(4):
            clock.advance(2.0)
            streams["w1"].sample(slo_sample(12 + i))
            col.drain()
        lost = col.events.events("worker_lost")
        assert len(lost) == 1, lost
        assert lost[0]["worker"] == "w0"
        assert lost[0]["severity"] == "error"
        kinds = [b["trigger"]["kind"] for b in flight.bundles()]
        assert kinds.count("worker_lost") == 1, kinds
        # Re-draining later never re-fires the same loss (w1 reports
        # cleanly, so only w0's single loss ever exists).
        streams["w1"].report({"completed": 15, "failed": 0})
        col.drain()
        clock.advance(10.0)
        col.drain()
        assert len(col.events.events("worker_lost")) == 1

    def test_finished_worker_never_lost(self, tmp_path):
        col, streams, clock = make_fleet(tmp_path, workers=("w0",))
        streams["w0"].sample(slo_sample(8))
        streams["w0"].report({"completed": 8, "failed": 0,
                              "harvest_records": 8})
        col.drain()
        clock.advance(60.0)
        assert col.check_liveness() == []
        rows = col.worker_rows()
        assert rows[0]["status"] == "ok"

    def test_crash_cell_reconciles_over_survivors(self, tmp_path):
        """The worker-failure satellite: a worker killed mid-soak must
        yield exactly one worker_lost incident and a merged report
        that still reconciles over the survivors — no hang, no
        double-count."""
        clock = FaultClock()
        flight = FlightRecorder(out_dir=str(tmp_path / "incidents"),
                                debounce_s=0.0, clock=clock)
        col, streams, _ = make_fleet(
            tmp_path, workers=("w0", "w1", "w2"), clock=clock,
            flight=flight)
        # All three run; w1 dies at completed=40 (mid-line write, the
        # kill -9 signature), the others finish cleanly.
        for wid, n in (("w0", 50), ("w1", 40), ("w2", 60)):
            streams[wid].sample(slo_sample(n, counts=(n, 0)))
        with open(streams["w1"].path, "a") as f:
            f.write('{"t": 2, "w": "w1", "kind": "sam')  # torn write
        col.drain()
        for i in range(4):
            clock.advance(2.0)
            for wid, n in (("w0", 50 + i), ("w2", 60 + i)):
                streams[wid].sample(slo_sample(n, counts=(n, 0)))
            col.drain()
        for wid, n in (("w0", 53), ("w2", 63)):
            streams[wid].sample(slo_sample(n, counts=(n, 0)))
            streams[wid].report({
                "completed": n, "failed": 0, "harvest_records": n,
                "recompiles_after_warmup": 0,
                "throughput_solves_per_s": 10.0})
        col.drain()
        report = col.report()
        assert report["workers_lost"] == ["w1"]
        assert report["reconciled"], report["reconciliation"]
        # Fleet completed counts the lost worker's LAST KNOWN total
        # exactly once; survivor harvest == survivor completed.
        assert report["fleet"]["completed"] == 53 + 40 + 63
        assert report["fleet"]["harvest_records"] == 53 + 63
        assert len(col.events.events("worker_lost")) == 1
        paths = [p for p in flight.bundles() if isinstance(p, str)]
        wl = [p for p in paths if "worker_lost" in os.path.basename(p)]
        assert len(wl) == 1, paths
        from porqua_tpu.obs import load_bundle

        bundle = load_bundle(wl[0])
        assert bundle["trigger"]["kind"] == "worker_lost"
        assert bundle["trigger"]["worker"] == "w1"
        assert bundle["counters"]["workers_lost"] == 1
        # The fleet section renders the incident the way the satellite
        # specifies: liveness verdict line + reconciliation verdict.
        text = fleet_section(report)
        assert "worker liveness: 2 ok, 1 lost" in text
        assert "LOST: w1" in text
        assert "reconciliation: OK" in text


# ---------------------------------------------------------------------------
# fleet SLO + rollups
# ---------------------------------------------------------------------------

class TestFleetSLOAndRollups:
    def test_fleet_burn_rate_fires_over_merged_windows(self, tmp_path):
        clock = FaultClock()
        engine = SLOEngine(default_slos(), clock=clock,
                           min_eval_interval_s=0.0)
        flight = FlightRecorder(out_dir=None, debounce_s=0.0,
                                clock=clock)
        col, streams, _ = make_fleet(tmp_path, clock=clock, slo=engine,
                                     flight=flight)
        streams["w0"].sample(slo_sample(100))
        streams["w1"].sample(slo_sample(100))
        col.drain()
        # Worker w1 starts failing hard; the availability burn crosses
        # the fast rule over the MERGED window even though w0 is fine.
        clock.advance(10.0)
        streams["w0"].sample(slo_sample(110))
        streams["w1"].sample(slo_sample(102, failed=90))
        col.drain()
        status = engine.status()
        assert status["alerts_fired"] >= 1, status
        alerts = col.events.events("slo_alert")
        assert any(e["state"] == "firing" for e in alerts)
        kinds = [b["trigger"]["kind"] for b in flight.bundles()]
        assert "slo_alert" in kinds

    def test_rollup_ring_is_bounded_with_exact_deltas(self, tmp_path):
        col, streams, clock = make_fleet(
            tmp_path, workers=("w0",), rollup_capacity=4,
            rollup_window_s=2.0)
        total = 0
        for i in range(12):
            total += 10
            streams["w0"].sample(slo_sample(total))
            clock.advance(2.0)
            col.drain()
        rolls = col.rollups()
        assert len(rolls) <= 4  # the memory bound
        # Every retained window carries exactly its own delta.
        assert all(r["completed"] == 10.0 for r in rolls[1:]), rolls
        assert col.snapshot()["rollup_windows"] <= 4

    def test_worker_gauges_and_fleet_exposition(self, tmp_path):
        import urllib.request

        col, streams, _ = make_fleet(tmp_path)
        streams["w0"].sample(slo_sample(7, counts=(5, 2, 0)),
                             hist={"solve_latency_seconds": {
                                 "le": (0.01, 0.1), "counts": [5, 2, 0],
                                 "sum": 0.1, "count": 7}},
                             vitals={"rss_bytes": 1.5e8, "open_fds": 33,
                                     "threads": 9, "queue_depth": 2})
        streams["w1"].sample(slo_sample(9))
        col.drain()
        gauges = col.worker_gauges()
        assert ({"worker": "w0"}, 7.0) in gauges["worker_completed"]
        assert ({"worker": "w0"}, 1.5e8) in gauges["worker_rss_bytes"]
        port = col.start_http()
        try:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            assert 'porqua_fleet_worker_completed{worker="w0"} 7' in text
            assert 'porqua_fleet_worker_up{worker="w1"} 1' in text
            assert "porqua_fleet_solve_latency_seconds_bucket" in text
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10).read())
            assert health["ok"] and health["workers"] == 2
        finally:
            col.stop_http()


# ---------------------------------------------------------------------------
# vitals
# ---------------------------------------------------------------------------

class TestVitals:
    def test_process_vitals_sane(self):
        v = process_vitals(queue_depth=5)
        assert v["queue_depth"] == 5
        assert v["threads"] >= 1
        assert v.get("rss_bytes", 1) > 0
        assert v.get("open_fds", 1) > 0

    def test_leak_fires_once_with_hysteresis(self):
        trend = VitalsTrend(min_samples=4, alpha_fast=0.6,
                            alpha_slow=0.05)
        fired = []
        for i in range(12):
            fired += trend.observe("w0", {"rss_bytes": 1000 * 1.4 ** i})
        firing = [e for e in fired if e["state"] == "firing"]
        assert len(firing) == 1
        assert firing[0]["kind"] == "vitals_anomaly"
        assert firing[0]["metric"] == "rss_bytes"
        # A flat tail clears it exactly once (hysteresis).
        flat = trend.status()["groups"]["w0/rss_bytes"]["ewma_fast"]
        resolved = []
        for _ in range(40):
            resolved += trend.observe("w0", {"rss_bytes": flat * 0.4})
        assert sum(1 for e in resolved
                   if e["state"] == "resolved") == 1
        st = trend.status()
        assert st["fired"] == 1 and st["resolved"] == 1

    def test_bursty_queue_depth_not_trended_by_default(self):
        # queue_depth oscillates by design (open-loop bursts between
        # batch drains); a ratio trend on it fired 15 false anomalies
        # in a clean 4-worker soak. Default judged set excludes it —
        # the samples still flow (gauges + rollup high-water marks).
        trend = VitalsTrend(min_samples=4, alpha_fast=0.6,
                            alpha_slow=0.05)
        events = []
        for i in range(60):
            events += trend.observe(
                "w0", {"queue_depth": 0 if i % 3 else 400,
                       "rss_bytes": 1e8})
        assert events == []
        assert "w0/queue_depth" not in trend.status()["groups"]

    def test_steady_process_never_fires(self):
        trend = VitalsTrend(min_samples=4)
        events = []
        for i in range(50):
            events += trend.observe(
                "w0", {"rss_bytes": 1e8 + (i % 3) * 1e5, "threads": 12})
        assert events == []

    def test_vitals_anomaly_is_flight_trigger_on_firing_edge_only(self):
        from porqua_tpu.obs import EventBus

        clock = FaultClock()
        bus = EventBus()
        flight = FlightRecorder(out_dir=None, debounce_s=0.0,
                                clock=clock)
        bus.add_listener(flight.on_event)
        trend = VitalsTrend(min_samples=4, alpha_fast=0.6,
                            alpha_slow=0.05, events=bus)
        for i in range(12):
            trend.observe("w0", {"rss_bytes": 1000 * 1.4 ** i})
        kinds = [b["trigger"]["kind"] for b in flight.bundles()]
        assert kinds == ["vitals_anomaly"]
        peak = trend.status()["groups"]["w0/rss_bytes"]["ewma_fast"]
        for _ in range(40):
            trend.observe("w0", {"rss_bytes": peak * 0.4})
        # The resolve transition is history, not an incident.
        kinds = [b["trigger"]["kind"] for b in flight.bundles()]
        assert kinds == ["vitals_anomaly"]

    def test_service_exports_vitals_gauges_and_healthz(self):
        import urllib.request

        import numpy as np

        from porqua_tpu.qp.canonical import CanonicalQP
        from porqua_tpu.serve.service import SolveService

        n = 4
        qp = CanonicalQP(
            P=np.eye(n, dtype=np.float32),
            q=np.zeros(n, np.float32),
            C=np.ones((1, n), np.float32),
            l=np.ones(1, np.float32), u=np.ones(1, np.float32),
            lb=np.zeros(n, np.float32), ub=np.ones(n, np.float32),
            var_mask=np.ones(n, np.float32),
            row_mask=np.ones(1, np.float32),
            constant=np.float32(0))
        service = SolveService(max_batch=4)
        with service:
            service.prewarm(qp)
            port = service.start_http()
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30
            ).read().decode()
            assert "porqua_serve_vitals_rss_bytes" in text
            assert "porqua_serve_vitals_threads" in text
            assert "porqua_serve_vitals_queue_depth 0" in text
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30).read())
            assert health["vitals"]["threads"] >= 1
            assert health["vitals"]["queue_depth"] == 0
            assert health["vitals"].get("rss_bytes", 1) > 0


# ---------------------------------------------------------------------------
# ledger + trend gate
# ---------------------------------------------------------------------------

class TestLedger:
    def test_row_roundtrip_and_rolling_median(self, tmp_path):
        path = str(tmp_path / "LEDGER.jsonl")
        for i, v in enumerate((2.0, 2.2, 2.4, 2.6, 2.8, 9.9)):
            ledger.append_row(path, ledger.ledger_row(
                "bench", {"vs_baseline": v}, run_id=f"r{i}",
                rev="abc1234", t=float(i)))
        rows = ledger.load_ledger(path)
        assert len(rows) == 6
        assert rows[0]["v"] == ledger.LEDGER_SCHEMA_VERSION
        assert rows[0]["rev"] == "abc1234"
        # Median over the last 5 rows, robust to the 9.9 outlier.
        assert ledger.rolling_median(rows, "vs_baseline",
                                     window=5) == 2.6
        assert ledger.rolling_median(rows, "missing") is None
        assert ledger.rolling_median(rows, "vs_baseline",
                                     kind="fleet_loadgen") is None
        assert ledger.load_ledger(str(tmp_path / "nope.jsonl")) == []

    def test_unknown_kind_refused(self):
        with pytest.raises(ValueError, match="unknown ledger kind"):
            ledger.ledger_row("mystery", {})

    def test_metrics_from_fleet_counts_workers_lost(self):
        report = {"workers": 4, "workers_lost": ["w1", "w3"],
                  "fleet": {"completed": 100}, "reconciled": True}
        flat = ledger.metrics_from_fleet(report)
        # The report carries ids; the trend series needs the count —
        # a crash cell must leave a workers_lost=1 point, not nothing.
        assert flat["workers_lost"] == 2
        assert flat["fleet.completed"] == 100
        assert ledger.metrics_from_fleet(
            {"workers_lost": []})["workers_lost"] == 0

    def test_metrics_extractors_flatten_dotted_paths(self):
        bench = {"value": 3.6, "vs_baseline": 2.5,
                 "config_serving": {"throughput_solves_per_s": 3000.0},
                 "xla_cost": {"flops": 1e12},
                 "device": "tpu:0"}
        flat = ledger.metrics_from_bench(bench)
        assert flat["config_serving.throughput_solves_per_s"] == 3000.0
        assert flat["xla_cost.flops"] == 1e12
        assert "device" not in flat
        assert ledger.nest_metrics(flat)["config_serving"][
            "throughput_solves_per_s"] == 3000.0

    @pytest.fixture()
    def gate(self):
        sys.path.insert(0, _SCRIPTS)
        try:
            import bench_gate
        finally:
            sys.path.remove(_SCRIPTS)
        return bench_gate

    def test_trend_gate_pass_and_fail(self, gate, tmp_path):
        path = str(tmp_path / "LEDGER.jsonl")
        base = gate._synthetic_baseline()
        for i in range(5):
            ledger.append_row(path, ledger.ledger_row(
                "bench", ledger.metrics_from_bench(base),
                run_id=f"r{i}", t=float(i)))
        good = json.loads(json.dumps(base))
        good["vs_baseline"] *= 0.95
        v = gate.check_trend(path, good, window=5)
        assert v["ok"], v["failed"]
        assert v["trend"]["rows_of_kind"] == 5
        bad = json.loads(json.dumps(base))
        bad["vs_baseline"] *= 0.4
        bad["config_compaction"]["te_drift"] = 1.0  # invariant break
        v_bad = gate.check_trend(path, bad, window=5)
        assert not v_bad["ok"]
        assert "headline_speedup" in v_bad["failed"]
        assert "compaction_te_parity" in v_bad["failed"]
        # The drift that pairwise gates miss: five slowly-degrading
        # rows, each within 0.7x of its predecessor, but the next step
        # falls below 0.7x of the window's median.
        drift_path = str(tmp_path / "DRIFT.jsonl")
        v0 = base["vs_baseline"]
        for i, scale in enumerate((1.0, 0.85, 0.72, 0.62, 0.53)):
            row = json.loads(json.dumps(base))
            row["vs_baseline"] = v0 * scale
            ledger.append_row(drift_path, ledger.ledger_row(
                "bench", ledger.metrics_from_bench(row),
                run_id=f"d{i}", t=float(i)))
        next_step = json.loads(json.dumps(base))
        next_step["vs_baseline"] = v0 * 0.45  # 0.85x of its predecessor
        v_drift = gate.check_trend(drift_path, next_step, window=5)
        assert "headline_speedup" in v_drift["failed"], v_drift

    def test_trend_retired_metric_ages_out_of_baseline(self, gate,
                                                       tmp_path):
        # A metric only rows OLDER than the window carry (renamed or
        # intentionally retired) must age out of the trend baseline —
        # not fail every future run as a coverage regression forever.
        path = str(tmp_path / "RETIRED.jsonl")
        base = gate._synthetic_baseline()
        old = ledger.metrics_from_bench(base)
        old["xla_cost.flops"] = 1e12  # carried only by the old rows
        for i in range(2):
            ledger.append_row(path, ledger.ledger_row(
                "bench", old, run_id=f"old{i}", t=float(i)))
        new = {k: v for k, v in ledger.metrics_from_bench(base).items()
               if k != "xla_cost.flops"}
        for i in range(5):
            ledger.append_row(path, ledger.ledger_row(
                "bench", new, run_id=f"new{i}", t=float(10 + i)))
        candidate = json.loads(json.dumps(base))
        candidate.get("xla_cost", {}).pop("flops", None)
        v = gate.check_trend(path, candidate, window=5)
        assert v["ok"], v["failed"]
        assert all(c["baseline"] is None for c in v["checks"]
                   if c["name"] == "xla_flops_drift"), v["checks"]

    def test_append_ledger_dispatches_extractor_by_kind(self, gate,
                                                        tmp_path):
        import subprocess

        path = str(tmp_path / "FLEET_LEDGER.jsonl")
        fleet_report = {"workers": 2, "workers_lost": [],
                        "duration_s": 10.0,
                        "fleet": {"completed": 3000, "failed": 0,
                                  "throughput_solves_per_s": 300.0},
                        "incident_bundles": 0, "reconciled": True}
        payload = str(tmp_path / "fleet_report.json")
        with open(payload, "w") as f:
            json.dump(fleet_report, f)
        out = subprocess.run(
            [sys.executable, os.path.join(_SCRIPTS, "bench_gate.py"),
             "--trend", path, "--trend-kind", "fleet_loadgen",
             "--payload", payload, "--append-ledger"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        rows = ledger.load_ledger(path)
        assert len(rows) == 1
        # A fleet payload lands a fleet row: kind + fleet.* metrics,
        # not an empty dict from the bench extractor missing its paths.
        assert rows[0]["kind"] == "fleet_loadgen"
        assert rows[0]["metrics"]["fleet.completed"] == 3000
        assert rows[0]["metrics"]["workers_lost"] == 0

    def test_trend_selftest_and_backfill(self, gate, tmp_path):
        assert gate._selftest() == 0
        sys.path.insert(0, _SCRIPTS)
        try:
            import trend_report
        finally:
            sys.path.remove(_SCRIPTS)
        path = str(tmp_path / "BF.jsonl")
        stats = trend_report.backfill(path)
        assert stats["appended"] >= 6
        assert trend_report.backfill(path)["appended"] == 0  # idempotent
        rows = ledger.load_ledger(path)
        ids = {r["run_id"] for r in rows}
        assert {"BENCH_r03", "BENCH_r05", "BENCH_GATE_r07",
                "SLO_r09.full_plane"} <= ids
        text = trend_report.render_trends(rows)
        assert "run ledger trajectory" in text
        assert "vs_baseline" in text


# ---------------------------------------------------------------------------
# the crash fault kind at the loadgen.worker seam + GC108
# ---------------------------------------------------------------------------

class TestSeamAndContract:
    def test_crash_kind_allowed_at_loadgen_worker_seam(self):
        spec = FaultSpec.make("loadgen.worker", "crash", start=3)
        assert spec.seam == "loadgen.worker"
        with pytest.raises(ValueError, match="cannot target"):
            FaultSpec.make("loadgen.worker", "device_lost")

    def test_injected_crash_fires_at_seeded_arrival(self):
        from porqua_tpu.resilience import faults as _faults

        scenario = _faults.Scenario(
            "crash-cell",
            faults=(_faults.FaultSpec.make("loadgen.worker", "crash",
                                           start=5),),
            seed=7)
        inj = _faults.install(_faults.FaultInjector(scenario))
        try:
            hits = 0
            with pytest.raises(_faults.InjectedCrash):
                while True:
                    if _faults.enabled():
                        _faults.fire("loadgen.worker", k=hits)
                    hits += 1
            assert hits == 5  # fired exactly at seeded hit index 5
            assert inj.exhausted()
        finally:
            _faults.uninstall()

    def test_gc108_clean(self):
        from porqua_tpu.analysis import contracts

        assert contracts.check_federation_identity() == []

    def test_worker_stream_never_raises_on_dead_sink(self, tmp_path):
        path = str(tmp_path / "s.jsonl")
        stream = WorkerStream(path, "w0")
        stream.hello(latency_le=[0.1])
        stream.close()
        stream.sample(slo_sample(1))  # post-close: counted, not raised
        assert stream.write_failures >= 1
        assert stream.records == 1
