"""Multi-chip sharding: sharded batch solve == single-chip batch solve.

Runs on the 8 virtual CPU devices configured in conftest; the same
program shards over real TPU ICI unchanged.
"""

import os
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from porqua_tpu.parallel import make_mesh, pad_batch_to_mesh, solve_qp_sharded
from porqua_tpu.qp import SolverParams, Status, solve_qp_batch, stack_qps
from porqua_tpu.qp.canonical import CanonicalQP

TIGHT = SolverParams(eps_abs=1e-8, eps_rel=1e-8, max_iter=10000)


def portfolio_qp(rng, n):
    X = rng.standard_normal((50, n)) * 0.01
    P = 2 * X.T @ X + 1e-4 * np.eye(n)
    q = -0.01 * rng.random(n)
    return CanonicalQP.build(
        P, q, C=np.ones((1, n)), l=np.ones(1), u=np.ones(1),
        lb=np.zeros(n), ub=np.ones(n), dtype=jnp.float64,
    )


@pytest.fixture
def batch(rng):
    return stack_qps([portfolio_qp(rng, 10) for _ in range(11)])


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_pad_batch_to_mesh(batch):
    padded, n_real = pad_batch_to_mesh(batch, 8)
    assert n_real == 11
    assert padded.P.shape[0] == 16


def test_sharded_solve_matches_single_chip(batch):
    mesh = make_mesh(8, axis_names=("dates",))
    sharded = solve_qp_sharded(batch, mesh, TIGHT)
    single = solve_qp_batch(batch, TIGHT)

    assert sharded.x.shape[0] == 11
    assert np.all(np.asarray(sharded.status) == Status.SOLVED)
    np.testing.assert_allclose(
        np.asarray(sharded.x), np.asarray(single.x), atol=1e-8
    )


def test_2d_mesh_benchmarks_by_dates(rng):
    """A (benchmarks x dates) grid sharded over a 2-D mesh."""
    qps = [portfolio_qp(rng, 8) for _ in range(8)]
    flat = stack_qps(qps)
    grid = jax.tree.map(lambda a: a.reshape((2, 4) + a.shape[1:]), flat)

    mesh = make_mesh(8, axis_names=("bench", "dates"), shape=(2, 4))
    from porqua_tpu.parallel import shard_qp_batch

    grid_sharded = shard_qp_batch(grid, mesh, n_batch_axes=2)

    from porqua_tpu.qp.solve import _solve_impl

    sol = jax.jit(
        jax.vmap(jax.vmap(lambda q: _solve_impl(q, TIGHT, None, None)))
    )(grid_sharded)
    ref = solve_qp_batch(flat, TIGHT)
    np.testing.assert_allclose(
        np.asarray(sol.x).reshape(8, -1), np.asarray(ref.x), atol=1e-8
    )


def test_pad_batch_smaller_than_mesh(rng):
    """Regression: batch smaller than half the mesh must still pad to a
    full multiple (a[:rem] under-padded when rem > n_real)."""
    small = stack_qps([portfolio_qp(rng, 6) for _ in range(3)])
    padded, n_real = pad_batch_to_mesh(small, 8)
    assert n_real == 3
    assert padded.P.shape[0] == 8

    mesh = make_mesh(8, axis_names=("dates",))
    sol = solve_qp_sharded(small, mesh, TIGHT)
    ref = solve_qp_batch(small, TIGHT)
    np.testing.assert_allclose(np.asarray(sol.x), np.asarray(ref.x), atol=1e-8)


def test_pad_slots_are_trivial(rng):
    """Filler slots must be near-free pinned-to-zero problems, not
    duplicated real solves."""
    small = stack_qps([portfolio_qp(rng, 6) for _ in range(3)])
    padded, n_real = pad_batch_to_mesh(small, 8)
    sol = solve_qp_batch(padded, TIGHT)
    filler_iters = np.asarray(sol.iters)[n_real:]
    real_iters = np.asarray(sol.iters)[:n_real]
    assert np.all(np.asarray(sol.x)[n_real:] == 0.0)
    assert filler_iters.max() <= real_iters.min()


def test_scan_l1_grid_sharded_matches_per_column(rng):
    """The coupled-dates x benchmarks grid engine: lax.scan over dates,
    vmap over benchmarks sharded on the mesh, must equal the
    single-column scan engine run per benchmark (SURVEY §7's
    scan-over-dates x vmap-over-benchmarks design)."""
    import jax.numpy as jnp

    from porqua_tpu.batch import (FIXED_UNIVERSE, solve_scan_l1,
                                  solve_scan_l1_grid)

    B, T, n = 4, 6, 8
    tc = 0.002
    cols = []
    for b in range(B):
        dates = []
        for t in range(T):
            X = rng.standard_normal((40, n)) * 0.01
            w_true = rng.dirichlet(np.ones(n))
            y = X @ w_true
            dates.append(CanonicalQP.build(
                2 * X.T @ X, -2 * X.T @ y, C=np.ones((1, n)),
                l=np.ones(1), u=np.ones(1), lb=np.zeros(n), ub=np.ones(n),
                dtype=jnp.float64))
        cols.append(stack_qps(dates))
    grid = jax.tree.map(lambda *a: jnp.stack(a), *cols)

    params = SolverParams(eps_abs=1e-8, eps_rel=1e-8, max_iter=20000)
    w_init = np.full((B, n), 1.0 / n)

    mesh = make_mesh(4, axis_names=("bench",))
    sharded = solve_scan_l1_grid(
        grid, n, w_init, tc, params=params, mesh=mesh,
        universes=FIXED_UNIVERSE)
    unsharded = solve_scan_l1_grid(
        grid, n, w_init, tc, params=params, mesh=None,
        universes=FIXED_UNIVERSE)
    np.testing.assert_allclose(
        np.asarray(sharded.x), np.asarray(unsharded.x), atol=1e-10)

    for b in range(B):
        col = jax.tree.map(lambda a: a[b], grid)
        ref = solve_scan_l1(col, n, w_init[b], tc, params=params,
                            universes=FIXED_UNIVERSE)
        assert np.all(np.asarray(ref.status) == Status.SOLVED)
        np.testing.assert_allclose(
            np.asarray(sharded.x[b]), np.asarray(ref.x), atol=1e-9)


def test_scan_l1_grid_rejects_uneven_mesh(rng):
    import jax.numpy as jnp

    from porqua_tpu.batch import solve_scan_l1_grid

    n = 4
    qp = CanonicalQP.build(np.eye(n), np.zeros(n), dtype=jnp.float64)
    grid = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (3, 2) + a.shape), qp)
    mesh = make_mesh(8, axis_names=("bench",))
    from porqua_tpu.batch import FIXED_UNIVERSE
    with pytest.raises(ValueError, match="divide evenly"):
        solve_scan_l1_grid(grid, n, np.zeros((3, n)), 0.001, mesh=mesh,
                           universes=FIXED_UNIVERSE)


def test_multihost_mesh_single_process_degenerates():
    # Single-process: the hybrid hosts x dates mesh collapses to
    # (1, n_local) and solves a sharded batch identically to 1-D.
    from porqua_tpu.parallel.mesh import init_distributed, make_multihost_mesh

    from porqua_tpu.tracking import build_tracking_qp, synthetic_universe

    assert init_distributed() == 1
    mesh = make_multihost_mesh()
    assert mesh.devices.shape == (1, len(jax.devices()))
    assert mesh.axis_names == ("hosts", "dates")

    Xs, ys = synthetic_universe(jax.random.PRNGKey(2), n_dates=8, window=24,
                                n_assets=12, dtype=jnp.float64)
    qp = jax.vmap(build_tracking_qp)(Xs, ys)
    # shard dates over the trailing (ICI) axis, replicate over hosts
    sharded = jax.tree.map(
        lambda a: jax.device_put(
            a, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("dates"))),
        qp,
    )
    sol = solve_qp_batch(sharded, SolverParams(
        max_iter=2000, eps_abs=1e-8, eps_rel=1e-8, linsolve="chol"))
    assert np.all(np.asarray(sol.status) == 1)
    ref = solve_qp_batch(qp, SolverParams(
        max_iter=2000, eps_abs=1e-8, eps_rel=1e-8, linsolve="chol"))
    np.testing.assert_allclose(np.asarray(sol.x), np.asarray(ref.x),
                               rtol=0, atol=1e-12)


def test_two_process_multihost():
    """The DCN axis for real (round-4 verdict item 8): TWO processes,
    each with 4 virtual CPU devices, joined via jax.distributed with a
    local coordinator — init_distributed's consistency check, the
    hosts x dates hybrid mesh at its true (2, 4) shape, a globally
    sharded batch, and per-process shard parity against an unsharded
    reference all run in the spawned workers (tests/multihost_worker.py)."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for i in range(2)
    ]
    outs = []
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"worker {i} rc={rc}\n{err[-2000:]}"
        assert f"MULTIHOST OK pid={i} procs=2 shard_rows=8" in out, out
