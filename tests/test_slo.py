"""The live operational plane (ISSUE 9): SLO engine + multi-window
burn-rate alerting on a stepped FaultClock, the incident flight
recorder's trigger/debounce/bundle contract, harvest-calibrated
convergence anomaly detection, the serve-stack wiring (``/healthz``
SLO status, ``/metrics`` gauges), the disabled-is-bit-identical pin,
and the GC106 jaxpr-identity contract."""

import json
import urllib.request

import numpy as np
import pytest

from porqua_tpu.obs import Observability
from porqua_tpu.obs.anomaly import AnomalyDetector
from porqua_tpu.obs.events import EventBus
from porqua_tpu.obs.flight import (
    DEFAULT_TRIGGERS,
    FlightRecorder,
    load_bundle,
)
from porqua_tpu.obs.slo import (
    SLO,
    BurnRateRule,
    SLOEngine,
    default_slos,
)
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.solve import SolverParams
from porqua_tpu.resilience.faults import FaultClock
from porqua_tpu.serve import BucketLadder, ServeMetrics, SolveService

PARAMS = SolverParams(max_iter=500, eps_abs=1e-5, eps_rel=1e-5,
                      polish=False, check_interval=25)
LADDER = BucketLadder(n_rungs=(8,), m_rungs=(4,))


def make_qp(n=6, m=2, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((2 * n, n))
    P = A.T @ A / (2 * n) + np.eye(n)
    q = rng.standard_normal(n)
    C = np.concatenate([np.ones((1, n)), rng.standard_normal((m - 1, n))])
    return CanonicalQP.build(
        P, q, C=C, l=np.full(m, -1.0), u=np.ones(m),
        lb=np.zeros(n), ub=np.ones(n))


#: One aggressive rule for deterministic state-machine tests: 10 s
#: short / 60 s long windows, threshold 10x, 5 s pending dwell, 20 s
#: resolve dwell.
RULE = BurnRateRule("test", long_s=60.0, short_s=10.0, burn_rate=10.0,
                    for_s=5.0, resolve_s=20.0)


def engine(slos=None, rules=(RULE,), clock=None, metrics=None,
           events=None):
    clock = FaultClock() if clock is None else clock
    metrics = ServeMetrics() if metrics is None else metrics
    eng = SLOEngine(
        slos or (SLO("availability", "availability", objective=0.99),),
        rules=rules, clock=clock, min_eval_interval_s=0.0)
    eng.bind(metrics, events=events)
    return eng, clock, metrics


# ---------------------------------------------------------------------------
# the burn-rate state machine, on a stepped clock
# ---------------------------------------------------------------------------

class TestSLOEngine:
    def test_no_traffic_no_alert(self):
        eng, clock, _ = engine()
        for _ in range(5):
            clock.advance(2.0)
            assert eng.evaluate() == []
        st = eng.status()
        assert st["firing"] == []
        assert st["slos"]["availability"]["compliance"] == 1.0

    def test_pending_then_firing_then_resolved(self):
        bus = EventBus()
        eng, clock, m = engine(events=bus)
        eng.evaluate()
        # Burn hard: 50% errors against a 1% budget = burn 50.
        m.inc("completed", 10)
        m.inc("failed", 10)
        clock.advance(2.0)
        evs = eng.evaluate()
        assert [e["state"] for e in evs] == ["pending"]  # for_s dwell
        # Condition persists past for_s=5 (counted from the pending
        # transition) -> firing (exactly once).
        clock.advance(6.0)
        evs = eng.evaluate()
        assert [e["state"] for e in evs] == ["firing"]
        assert eng.status()["firing"] == ["availability/test"]
        clock.advance(1.0)
        assert eng.evaluate() == []  # still firing, no re-emit
        # The bleeding stops: the short window goes clean 12 s later,
        # but resolve_s=20 must elapse CLEAR before the resolve emits.
        m.inc("completed", 5000)
        clock.advance(12.0)
        assert eng.evaluate() == []  # clear, inside the resolve dwell
        clock.advance(21.0)
        evs = eng.evaluate()
        assert [e["state"] for e in evs] == ["resolved"]
        assert eng.status()["firing"] == []
        kinds = [(e["kind"], e["state"]) for e in bus.events("slo_alert")]
        assert kinds == [("slo_alert", "pending"),
                         ("slo_alert", "firing"),
                         ("slo_alert", "resolved")]

    def test_multi_window_and_gating(self):
        # A long-ago burst still inside the long window but outside
        # the short one: the long window burns, the short is clean ->
        # no alert (the AND gate is what stops stale paging).
        eng, clock, m = engine()
        eng.evaluate()
        m.inc("completed", 10)
        m.inc("failed", 10)
        clock.advance(2.0)
        eng.evaluate()
        assert eng.status()["slos"]["availability"]["alerts"]["test"][
            "state"] == "pending"
        # 15 s of clean traffic pushes the burst out of the 10 s short
        # window while the 60 s long window still remembers it.
        m.inc("completed", 1000)
        clock.advance(15.0)
        assert eng.evaluate() == []
        alert = eng.status()["slos"]["availability"]["alerts"]["test"]
        assert alert["state"] == "inactive"  # pending cancelled
        assert alert["burn_long"] > 0.0
        assert alert["burn_short"] == 0.0

    def test_flap_debounce_keeps_one_firing_alert(self):
        eng, clock, m = engine()
        eng.evaluate()
        m.inc("completed", 10)
        m.inc("failed", 90)
        clock.advance(2.0)
        eng.evaluate()
        clock.advance(5.0)
        evs = eng.evaluate()
        assert [e["state"] for e in evs] == ["firing"]
        fired = eng.status()["alerts_fired"]
        # Flicker: clean for a bit (inside resolve_s), then burn again
        # — the clear timer must reset WITHOUT a resolve/fire pair.
        for _ in range(3):
            m.inc("completed", 2000)
            clock.advance(10.0)
            assert eng.evaluate() == []
            m.inc("failed", 2000)
            clock.advance(2.0)
            assert eng.evaluate() == []
        assert eng.status()["alerts_fired"] == fired
        assert eng.status()["firing"] == ["availability/test"]

    def test_latency_slo_reads_histogram_edges(self):
        m = ServeMetrics(latency_buckets=(0.01, 0.05, 0.25, 1.0))
        clock = FaultClock()
        eng = SLOEngine(
            (SLO("latency", "latency", objective=0.9,
                 latency_target_s=0.05),),
            rules=(RULE,), clock=clock, min_eval_interval_s=0.0)
        eng.bind(m)
        eng.evaluate()
        # 12 fast, 8 slow: 40% over target vs a 10% budget = burn 4.
        for _ in range(12):
            m.observe_latency(0.02)
        for _ in range(8):
            m.observe_latency(0.6)
        clock.advance(2.0)
        eng.evaluate()
        st = eng.status()["slos"]["latency"]
        assert st["effective_target_s"] == 0.05
        assert st["compliance"] == pytest.approx(0.6)
        assert st["alerts"]["test"]["burn_short"] == pytest.approx(4.0)

    def test_wrong_answers_budget_is_zero(self):
        eng, clock, m = engine(slos=default_slos())
        eng.evaluate()
        m.inc("completed", 10000)
        m.inc("validation_failures", 1)
        clock.advance(2.0)
        eng.evaluate()
        st = eng.status()["slos"]["wrong_answers"]
        # One wrong answer in 10k against an empty budget: burn is
        # astronomically over any threshold.
        assert st["alerts"]["test"]["burn_short"] > 1e4

    def test_window_reset_restarts_sliding_windows(self):
        eng, clock, m = engine()
        eng.evaluate()
        m.inc("failed", 100)
        clock.advance(2.0)
        eng.evaluate()
        assert eng.status()["slos"]["availability"]["compliance"] < 1.0
        # The loadgen protocol: reset after warmup. Counters regress;
        # the engine must drop its history instead of computing
        # negative deltas.
        m.reset_window()
        clock.advance(2.0)
        eng.evaluate()
        m.inc("completed", 10)
        clock.advance(2.0)
        assert eng.evaluate() == []
        assert eng.status()["slos"]["availability"]["compliance"] == 1.0

    def test_expired_requests_burn_availability(self):
        # A deadline storm with no retry layer increments ONLY the
        # `expired` counter — it must still burn the availability
        # budget (review fix: expired was invisible to the SLO).
        eng, clock, m = engine()
        eng.evaluate()
        m.inc("completed", 10)
        m.inc("expired", 10)
        clock.advance(2.0)
        eng.evaluate()
        st = eng.status()["slos"]["availability"]
        assert st["compliance"] == pytest.approx(0.5)
        assert st["alerts"]["test"]["burn_short"] > 10.0

    def test_sample_thinning_spans_long_window(self):
        # max_samples=8 with a 60 s long window: per-second evaluation
        # must NOT evict the window's baseline (review fix: fast eval
        # cadence silently truncated the long window). Thinning keeps
        # the buffer spanning the window at coarser resolution.
        eng, clock, m = engine(rules=(RULE,))
        eng._max_samples = 8
        eng._min_spacing = eng._max_window * 1.5 / 6
        eng.evaluate()
        m.inc("failed", 50)  # old burst
        clock.advance(1.0)
        eng.evaluate()
        # 95 s of clean per-second evaluations: the burst leaves the
        # 60 s window even at the thinned ~15 s sample granularity
        # (window resolution degrades by at most one spacing slot).
        for _ in range(95):
            m.inc("completed", 10)
            clock.advance(1.0)
            eng.evaluate()
        alert = eng.status()["slos"]["availability"]["alerts"]["test"]
        # The burst is now outside BOTH windows: burn must have decayed
        # to ~0 — and with a retained baseline the long-window figure
        # is a real windowed delta, not a since-forever one.
        assert alert["burn_long"] < 1.0
        assert len(eng._samples) <= 8

    def test_gauges_shape(self):
        eng, clock, m = engine()
        eng.evaluate()
        g = eng.gauges()
        assert g["slo_compliance_availability"] == 1.0
        assert g["slo_alert_state_availability_test"] == 0.0
        assert "slo_burn_rate_availability_test_short" in g
        assert "slo_burn_rate_availability_test_long" in g


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_dump_exactly_once_per_debounce_window(self):
        clock = FaultClock()
        bus = EventBus()
        rec = FlightRecorder(out_dir=None, debounce_s=10.0, clock=clock)
        rec.attach(metrics=ServeMetrics())
        bus.add_listener(rec.on_event)
        # A repeated trigger inside one debounce window dumps ONCE.
        for _ in range(5):
            bus.emit("breaker_open", "error", primary="cpu:0",
                     fallback="cpu:1", failures=2)
            clock.advance(1.0)
        assert len(rec.bundles()) == 1
        assert rec.suppressed == 4
        # The next window re-arms.
        clock.advance(10.0)
        bus.emit("retry_giveup", "error", request_id="r9",
                 reason="deadline")
        bundles = rec.bundles()
        assert len(bundles) == 2
        assert bundles[1]["trigger"]["kind"] == "retry_giveup"

    def test_stateful_triggers_fire_only_on_firing_edge(self):
        rec = FlightRecorder(out_dir=None, debounce_s=0.0)
        rec.on_event({"kind": "slo_alert", "state": "pending"})
        rec.on_event({"kind": "slo_alert", "state": "resolved"})
        rec.on_event({"kind": "convergence_anomaly", "state": "resolved"})
        assert rec.bundles() == []
        rec.on_event({"kind": "slo_alert", "state": "firing",
                      "slo": "availability", "rule": "fast"})
        assert len(rec.bundles()) == 1

    def test_non_trigger_kinds_ignored_and_disarm(self):
        rec = FlightRecorder(out_dir=None, debounce_s=0.0)
        rec.on_event({"kind": "compile", "severity": "info"})
        rec.on_event({"kind": "deadline_expired", "severity": "warn"})
        assert rec.bundles() == []
        rec.disarm()
        rec.on_event({"kind": "breaker_open", "severity": "error"})
        assert rec.bundles() == []
        rec.arm()
        rec.on_event({"kind": "breaker_open", "severity": "error"})
        assert len(rec.bundles()) == 1

    def test_bundle_self_contained_and_disk_bounded(self, tmp_path):
        clock = FaultClock()
        obs = Observability()
        metrics = ServeMetrics()
        rec = FlightRecorder(out_dir=str(tmp_path), debounce_s=1.0,
                             max_bundles=2, clock=clock)
        rec.attach(metrics=metrics, obs=obs, params=PARAMS)
        obs.events.add_listener(rec.on_event)
        metrics.inc("completed", 7)
        rec.record_solve({"v": 1, "status": 1, "iters": 75,
                          "bucket": "8x4"})
        rec.record_snapshot(metrics.snapshot())
        obs.events.emit("probe_failure", "warn", device="cpu:1")
        for i in range(4):
            obs.events.emit("breaker_open", "error", primary="cpu:1",
                            fallback="cpu:0", failures=2, round=i)
            clock.advance(2.0)
        paths = rec.bundles()
        # 4 windows -> 4 dumps, but only the newest max_bundles=2
        # survive on disk (retention pruned the rest).
        assert len(paths) == 2
        import os

        assert all(os.path.exists(p) for p in paths)
        assert len(list(tmp_path.iterdir())) == 2
        b = load_bundle(paths[-1])
        assert b["trigger"]["kind"] == "breaker_open"
        assert b["counters"]["completed"] == 7
        assert b["solves"][0]["iters"] == 75
        assert b["snapshots"][0]["completed"] == 7
        assert "cpu:1" in b["breaker_history"]
        assert b["config"]["fingerprint"]
        assert any(e["kind"] == "probe_failure" for e in b["events"])

    def test_trigger_inventory_default(self):
        assert set(DEFAULT_TRIGGERS) == {
            "breaker_open", "retry_giveup", "validation_failed",
            "sanitizer_violation", "harvest_sink_failed", "slo_alert",
            "convergence_anomaly",
            # The fleet plane (obs/federation.py, obs/vitals.py): a
            # crashed loadgen shard or a leaking worker is an incident.
            "worker_lost", "vitals_anomaly",
            # The calibration plane (obs/calibrate.py): a promoted
            # route table the guard window shot down is an incident.
            "route_rollback"}

    def test_failed_dump_does_not_consume_debounce(self, tmp_path):
        # Review fix: a dump that fails to write must not spend the
        # debounce window — the next trigger retries instead of the
        # whole incident going unrecorded.
        clock = FaultClock()
        rec = FlightRecorder(out_dir=str(tmp_path), debounce_s=30.0,
                             clock=clock)
        rec.attach(metrics=ServeMetrics())
        rec.out_dir = str(tmp_path / "gone")  # unwritable: missing dir
        rec.on_event({"kind": "breaker_open", "severity": "error"})
        assert rec.counters()["flight_write_failures"] == 1
        assert rec.bundles() == []
        rec.out_dir = str(tmp_path)  # disk "recovers"
        clock.advance(1.0)           # well inside the debounce window
        rec.on_event({"kind": "breaker_open", "severity": "error"})
        assert len(rec.bundles()) == 1

    def test_listener_failure_counted_not_raised(self):
        bus = EventBus()

        def bad_listener(event):
            raise RuntimeError("boom")

        bus.add_listener(bad_listener)
        bus.emit("breaker_open", "error")  # must not raise
        assert bus.listener_failures == 1


# ---------------------------------------------------------------------------
# anomaly detection
# ---------------------------------------------------------------------------

AGG = {"groups": [{"bucket": "8x4", "eps_abs": 1e-5,
                   "iters": {"p50": 60.0, "p95": 100.0, "max": 150.0},
                   "wasted_iteration_fraction": 0.1, "count": 64}]}


class TestAnomaly:
    def test_fires_once_and_resolves_with_hysteresis(self):
        bus = EventBus()
        det = AnomalyDetector.from_aggregate(
            AGG, alpha=0.5, iters_factor=1.5, min_samples=4,
            events=bus)
        # Baseline band: 100 * 1.5 = 150 iters. Healthy traffic first.
        for _ in range(4):
            assert det.observe("8x4", 1e-5, iters=80) is None
        # Drift: EWMA climbs past the band -> ONE firing event.
        fired = [det.observe("8x4", 1e-5, iters=600) for _ in range(6)]
        events = [e for e in fired if e is not None]
        assert len(events) == 1 and events[0]["state"] == "firing"
        assert det.status()["anomalous"] == ["8x4@1e-05"]
        # Recovery: EWMA decays back under clear_fraction * band.
        resolved = [det.observe("8x4", 1e-5, iters=60)
                    for _ in range(12)]
        events = [e for e in resolved if e is not None]
        assert len(events) == 1 and events[0]["state"] == "resolved"
        assert det.status()["anomalous"] == []
        kinds = [(e["kind"], e["state"])
                 for e in bus.events("convergence_anomaly")]
        assert kinds == [("convergence_anomaly", "firing"),
                         ("convergence_anomaly", "resolved")]

    def test_waste_band_breach(self):
        det = AnomalyDetector.from_aggregate(
            AGG, alpha=1.0, waste_margin=0.25, min_samples=2)
        # iters fine (under the band), but 80 iters over 8 segments of
        # 25 = 0.6 waste vs band 0.1 + 0.25.
        ev = None
        for _ in range(3):
            ev = det.observe("8x4", 1e-5, iters=80, segments=8,
                             check_interval=25) or ev
        assert ev is not None and ev["state"] == "firing"

    def test_unknown_group_counted_never_judged(self):
        det = AnomalyDetector.from_aggregate(AGG, min_samples=1)
        for _ in range(10):
            assert det.observe("64x16", 1e-3, iters=99999) is None
        st = det.status()
        assert st["unknown_group"] == 10
        assert st["fired"] == 0

    def test_from_harvest_roundtrip(self, tmp_path):
        from porqua_tpu.obs import HarvestSink, solve_record

        path = str(tmp_path / "h.jsonl.gz")
        with HarvestSink(path) as sink:
            for i in range(8):
                sink.emit(solve_record(
                    "serve", 8, 4, 1, 50 + i, 1e-6, 1e-6, 0.0,
                    bucket="8x4", eps_abs=1e-5, check_interval=25,
                    segments=3))
        det = AnomalyDetector.from_harvest(path)
        assert ("8x4", 1e-5) in det.baseline
        assert det.baseline[("8x4", 1e-5)]["iters_p95"] > 50


# ---------------------------------------------------------------------------
# metrics satellite: configurable latency buckets
# ---------------------------------------------------------------------------

class TestLatencyBuckets:
    def test_custom_ladder_validated(self):
        with pytest.raises(ValueError):
            ServeMetrics(latency_buckets=())
        with pytest.raises(ValueError):
            ServeMetrics(latency_buckets=(0.1, 0.1))
        with pytest.raises(ValueError):
            ServeMetrics(latency_buckets=(0.5, 0.1))

    def test_default_preserved(self):
        from porqua_tpu.serve.metrics import LATENCY_BUCKETS_S

        m = ServeMetrics()
        assert m.histograms()["solve_latency_seconds"]["le"] \
            == LATENCY_BUCKETS_S

    def test_slo_sample_schema(self):
        m = ServeMetrics(latency_buckets=(0.1, 1.0))
        m.inc("completed", 3)
        m.observe_latency(0.05)
        m.observe_latency(5.0)
        s = m.slo_sample()
        assert s["completed"] == 3
        assert s["latency_le"] == (0.1, 1.0)
        assert s["latency_counts"] == (1, 0, 1)
        assert s["latency_count"] == 2


# ---------------------------------------------------------------------------
# serve-stack wiring (live service on the CPU backend)
# ---------------------------------------------------------------------------

def live_plane_service(tmp_path=None, **kw):
    slo = SLOEngine(default_slos(latency_target_s=10.0),
                    min_eval_interval_s=0.0)
    flight = FlightRecorder(
        out_dir=None if tmp_path is None else str(tmp_path),
        debounce_s=0.0)
    anomaly = AnomalyDetector.from_aggregate(AGG, min_samples=2)
    return SolveService(params=PARAMS, ladder=LADDER, max_batch=8,
                        max_wait_ms=5.0, slo=slo, flight=flight,
                        anomaly=anomaly, **kw), slo, flight, anomaly


class TestServiceWiring:
    def test_disabled_plane_is_bit_identical(self):
        qp = make_qp()
        with SolveService(params=PARAMS, ladder=LADDER,
                          max_batch=8) as bare:
            x_bare = bare.solve(qp, timeout=60).x
        svc, slo, flight, anomaly = live_plane_service()
        with svc:
            x_live = svc.solve(qp, timeout=60).x
        # The plane is host bookkeeping: the answer bytes must be THE
        # answer bytes (GC106 pins the jaxpr half of this claim).
        assert x_live.tobytes() == x_bare.tobytes()
        assert slo.status()["evaluations"] >= 1
        assert anomaly.status()["observed"] == 1

    def test_healthz_and_metrics_carry_slo_status(self):
        svc, slo, flight, anomaly = live_plane_service()
        with svc:
            for seed in range(4):
                svc.solve(make_qp(seed=seed), timeout=60)
            port = svc.start_http(port=0)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                health = json.loads(r.read())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                text = r.read().decode()
        assert "slo" in health
        assert health["slo"]["firing"] == []
        assert health["slo"]["slos"]["availability"]["compliance"] == 1.0
        assert health["flight_bundles"] == 0
        assert "anomalies_fired" in health
        # Gauges in the exposition, typed gauge.
        assert ("# TYPE porqua_serve_slo_compliance_availability gauge"
                in text)
        assert "porqua_serve_slo_alert_state_availability_fast 0" in text
        assert "porqua_serve_slo_burn_rate_latency_fast_short" in text
        assert "# TYPE porqua_serve_slo_alerts_fired counter" in text

    def test_anomaly_feeds_flight_through_service_bus(self, tmp_path):
        svc, slo, flight, anomaly = live_plane_service(tmp_path)
        with svc:
            # 8x4 bucket at eps 1e-5 matches AGG's baseline group;
            # drive enough solves that the (converged) iteration EWMA
            # exceeds nothing — then force the breach synthetically
            # through the detector's own observe path with the
            # service's bus attached.
            svc.solve(make_qp(), timeout=60)
            for _ in range(4):
                anomaly.observe("8x4", 1e-5, iters=5000, segments=200,
                                check_interval=25)
        bundles = flight.bundles()
        assert len(bundles) >= 1
        b = load_bundle(bundles[0])
        assert b["trigger"]["kind"] == "convergence_anomaly"
        assert b["trigger"]["state"] == "firing"
        assert b["anomaly"]["fired"] >= 1

    def test_classic_dispatch_feeds_batch_executed_segments(self):
        # Review fix: a classic fused batch steps every lane to the
        # batch maximum, so the anomaly waste EWMA must divide by the
        # BATCH-executed segment count — per-lane ceil(iters/ci) read
        # ~zero waste for every lane and blinded the detector to
        # straggler drift.
        anomaly = AnomalyDetector.from_aggregate(AGG, min_samples=1)
        svc = SolveService(params=PARAMS, ladder=LADDER, max_batch=8,
                           max_wait_ms=200.0, anomaly=anomaly)
        with svc:
            # One coalesced batch of problems with a spread of
            # per-lane iteration counts (different conditioning).
            tickets = [svc.submit(make_qp(seed=s)) for s in range(8)]
            results = [svc.result(t, timeout=120) for t in tickets]
        iters = [r.iters for r in results]
        assert max(iters) > min(iters)  # a real spread, else vacuous
        groups = anomaly.status()["groups"]
        # Untagged serve requests are accounted under the shared
        # "default" tenant lane since the tenancy plane landed.
        key = "default/8x4@1e-05"
        assert key in groups
        # Fast lanes paid the straggler's segments: mean waste over
        # the batch must be visibly nonzero (per-lane derivation
        # would leave it under (ci-1)/iters ~ 0.5 only by accident —
        # check against the exact batch-max expectation instead).
        ci = PARAMS.check_interval
        exec_segs = -(-max(iters) // ci)
        expected = [1.0 - it / (exec_segs * ci) for it in iters]
        assert any(e > 0.2 for e in expected)
        # Pin the batch-max semantics exactly: the detector's EWMA
        # must equal the one folded from batch-executed waste, in
        # lane order (per-lane derivation gives a different number).
        ewma = expected[0]
        for e in expected[1:]:
            ewma += 0.2 * (e - ewma)
        assert groups[key]["ewma_waste"] == pytest.approx(ewma, abs=1e-3)

    def test_gc106_contract_clean(self):
        from porqua_tpu.analysis import contracts

        assert contracts.check_observability_identity() == []
